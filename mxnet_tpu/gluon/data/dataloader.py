"""DataLoader (reference: `python/mxnet/gluon/data/dataloader.py`).

The reference forks `num_workers` Python processes with shared-memory NDArray
return; this build keeps BOTH execution models:

  * `num_workers>0, thread_pool=False` (reference default): forked worker
    PROCESSES — the only way a GIL-bound python transform chain scales
    past one core.  Workers run the dataset+batchify on numpy and ship
    numpy back; device arrays are created in the parent.  The transform
    chain must stay host-side (numpy) inside workers — a forked child must
    never touch jax/XLA (the runtime's threads do not survive fork), and
    the worker raises a clear error if a sample does.
  * `thread_pool=True`: the thread-pool prefetcher (numpy releases the
    GIL for the heavy parts) — same structure as the reference's
    `PrefetcherIter` (`src/io/iter_prefetcher.h`), zero process overhead.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ... import telemetry as _telemetry
from ...ndarray import ndarray as _nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

_M_WAIT = _telemetry.histogram(
    "dataloader_wait_seconds", "time the training loop spent blocked "
    "waiting for the next HOST batch — compare against "
    "trainer_step_seconds to tell input-bound from compute-bound steps, "
    "and against device_prefetch_wait_seconds to tell host batch "
    "production from H2D staging")
# labeled stage="host": the device-side staging pipeline
# (mx.dataflow.prefetch_to_mesh) reports the same gauge under
# stage="device", so telemetry_report's input-stall attribution can name
# WHICH pipeline stage starved the consumer
_M_DEPTH = _telemetry.gauge(
    "dataloader_prefetch_depth", "batches buffered ahead of the consumer "
    "(0 while the consumer is starved = input-bound); fanned out by stage: "
    "host (DataLoader worker batches) vs device (mesh-staged arrays)"
).labels(stage="host")

__all__ = ["DataLoader", "default_batchify_fn", "numpy_batchify_fn",
           "in_worker"]

_IN_WORKER = False


def in_worker():
    """True inside a forked DataLoader worker process. Dataset __getitem__
    implementations use this to return host numpy instead of device
    arrays — jax/XLA must not run in a forked child."""
    return _IN_WORKER


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    if isinstance(data[0], NDArray):
        return _nd.array(np.stack([d.asnumpy() for d in data]))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return _nd.array(arr)


def numpy_batchify_fn(data):
    """Worker-process batchify: stacks to NUMPY (device arrays cannot be
    created in a forked child — jax state does not survive fork)."""
    if isinstance(data[0], tuple):
        return tuple(numpy_batchify_fn(list(x)) for x in zip(*data))
    if isinstance(data[0], NDArray):
        raise TypeError(
            "DataLoader worker produced an NDArray: with num_workers>0 the "
            "transform chain must stay host-side (numpy) — jax/XLA cannot "
            "run in a forked worker. Use numpy transforms (gluon.data."
            "vision.transforms are numpy-backed) or thread_pool=True.")
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _to_device_tree(batch):
    if isinstance(batch, tuple):
        return tuple(_to_device_tree(b) for b in batch)
    return batch if isinstance(batch, NDArray) else _nd.array(batch)


def _assert_numpy_tree(batch):
    """Reject device arrays produced inside a forked worker — whatever the
    batchify_fn, the answer crossing the fork must be host numpy."""
    if isinstance(batch, tuple):
        for b in batch:
            _assert_numpy_tree(b)
        return
    if isinstance(batch, NDArray):
        raise TypeError(
            "DataLoader worker produced an NDArray: with num_workers>0 the "
            "transform/batchify chain must stay host-side (numpy) — "
            "jax/XLA cannot run in a forked worker. Use numpy transforms "
            "or thread_pool=True.")


def _worker_loop(dataset, batchify_fn, key_q, data_q, seed):
    """Forked worker body: indices in, (idx, numpy batch | error) out."""
    global _IN_WORKER
    _IN_WORKER = True                   # datasets switch to numpy returns
    # fork copies the parent RNG state into EVERY worker: reseed per worker
    # or all workers draw identical crop/flip augmentation streams
    np.random.seed(seed)
    while True:
        item = key_q.get()
        if item is None:
            return
        idx, indices = item
        try:
            batch = batchify_fn([dataset[i] for i in indices])
            _assert_numpy_tree(batch)
            data_q.put((idx, batch, None))
        except Exception as e:          # noqa: BLE001 — relayed to parent
            data_q.put((idx, None, f"{type(e).__name__}: {e}"))


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None else 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if not _telemetry._enabled:
            yield from self._iter_impl()
            return
        # batch-wait accounting: the gap between the consumer asking for a
        # batch and one being ready is exactly the input stall the train
        # step experiences
        it = self._iter_impl()
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            _M_WAIT.observe(time.perf_counter() - t0)
            yield batch

    def _iter_impl(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        if not self._thread_pool:
            yield from self._iter_processes()
            return
        # threaded prefetch pipeline
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = queue.Queue()
            batches = iter(self._batch_sampler)
            stop = object()
            # depth = completed - consumed, from done callbacks — the queue
            # itself holds every future of the epoch up front, so qsize()
            # would report batches-remaining, not prefetch depth. Separate
            # monotonic counters (not one +/- cell) because the consumer's
            # result() can return BEFORE the done callback runs; the raw
            # difference dips to -1 transiently and self-corrects instead
            # of accumulating a phantom +1 per raced batch.
            counts_lock = threading.Lock()
            counts = [0, 0]     # [completed, consumed], tracked futures only

            def _mark_ready(_):
                with counts_lock:
                    counts[0] += 1

            def submitter():
                for indices in batches:
                    fut = pool.submit(self._load_batch, indices)
                    if _telemetry._enabled:
                        fut._tele_tracked = True
                        fut.add_done_callback(_mark_ready)
                    futures.put(fut)
                futures.put(stop)

            t = threading.Thread(target=submitter, daemon=True)
            t.start()
            while True:
                fut = futures.get()
                if fut is stop:
                    break
                batch = fut.result()
                if _telemetry._enabled and getattr(fut, "_tele_tracked",
                                                   False):
                    with counts_lock:
                        counts[1] += 1
                        depth = max(0, counts[0] - counts[1])
                    _M_DEPTH.set(depth)
                yield batch
            t.join()

    def _respawn_or_raise(self, workers, dead, respawns, ctx, bfn,
                          key_q, data_q, inflight):
        """A worker died silently (segfault / OOM-kill) with work
        outstanding. With mx.resilience enabled and retry budget left,
        replace the dead process(es) and re-enqueue every in-flight batch
        (duplicates from still-live workers dedupe at receipt); otherwise
        raise the classic fatal error. Returns (workers, respawns)."""
        from ... import resilience as _resilience
        policy = _resilience.RetryPolicy() if _resilience._enabled else None
        if policy is None or respawns + 1 >= policy.max_attempts:
            raise RuntimeError(
                f"DataLoader worker (pid {dead[0].pid}) died with exit "
                f"code {dead[0].exitcode} without reporting a result"
                + (f" ({respawns} respawn(s) already used)" if respawns
                   else "")) from None
        respawns += 1
        import sys as _sys
        print(f"mx.resilience: DataLoader worker (pid {dead[0].pid}, exit "
              f"code {dead[0].exitcode}) died — respawning and re-queuing "
              f"{len(inflight)} in-flight batch(es) (respawn "
              f"{respawns}/{policy.max_attempts - 1})", file=_sys.stderr)
        if _telemetry._enabled:
            _resilience._M_RETRIES.labels(site="dataloader-respawn").inc()
        workers = [w for w in workers if w.is_alive()]
        for w in dead:
            w.join(timeout=1)           # reap the corpse
        import warnings
        with warnings.catch_warnings():
            # same accepted fork caveat as the initial spawn: workers obey
            # the numpy-only contract, so the jax fork warning is noise
            warnings.filterwarnings("ignore", message=".*fork.*")
            for _ in dead:
                w = ctx.Process(
                    target=_worker_loop,
                    args=(self._dataset, bfn, key_q, data_q,
                          int(np.random.randint(0, 2 ** 31 - 1))),
                    daemon=True)
                w.start()
                workers.append(w)
        for item in list(inflight.items()):
            key_q.put(item)             # may duplicate: receipt dedupes
        return workers, respawns

    def _iter_processes(self):
        """Forked-worker pipeline (reference: _MultiWorkerIter): tasks fan
        out to `num_workers` processes, results reorder by batch index so
        iteration order matches num_workers=0 exactly. A worker that dies
        without reporting (segfault/OOM-kill) is fatal by default; with
        mx.resilience enabled it is respawned and its in-flight work
        re-enqueued, up to the RetryPolicy attempt budget."""
        import multiprocessing as mp

        ctx = mp.get_context("fork")    # fork: closures/lambdas in
        #                                 transforms need no pickling
        key_q = ctx.Queue()
        data_q = ctx.Queue()
        bfn = self._batchify_fn
        if bfn is default_batchify_fn:
            bfn = numpy_batchify_fn     # device arrays can't cross fork
        base_seed = int(np.random.randint(0, 2 ** 31 - 1))
        workers = [ctx.Process(target=_worker_loop,
                               args=(self._dataset, bfn, key_q, data_q,
                                     (base_seed + i) % (2 ** 32)),
                               daemon=True)
                   for i in range(self._num_workers)]
        # jax warns that fork from a multithreaded process can deadlock —
        # true IF the child touches jax, which the numpy-only worker
        # contract (numpy_batchify_fn raises on NDArray) forbids. Same
        # accepted caveat as the reference's fork+CUDA DataLoader.
        import warnings
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*fork.*", category=RuntimeWarning)
            warnings.filterwarnings(
                "ignore", message=".*fork.*", category=DeprecationWarning)
            for w in workers:
                w.start()
        try:
            batches = iter(enumerate(self._batch_sampler))
            inflight = {}      # idx -> indices: sent to a worker, no result
            buf = {}
            respawns = 0

            def _send():
                item = next(batches, None)
                if item is None:
                    return False
                inflight[item[0]] = item[1]
                key_q.put(item)
                return True

            for _ in range(max(self._prefetch, 1)):
                if not _send():
                    break
            next_yield = 0
            while True:
                if next_yield in buf:
                    if _telemetry._enabled:
                        _M_DEPTH.set(len(buf))
                    yield _to_device_tree(buf.pop(next_yield))
                    next_yield += 1
                    continue
                if _telemetry._enabled:
                    _M_DEPTH.set(0)     # consumer is starved: input-bound
                if not inflight:        # nothing in flight, nothing buffered
                    break
                from ... import config as _config
                stall_limit = float(_config.get("dataloader_timeout"))
                waited = 0.0
                while True:             # bounded get: a worker that died OR
                    try:                # deadlocked must not hang us forever
                        idx, batch, err = data_q.get(timeout=1)
                        break
                    except queue.Empty:
                        waited += 1
                        dead = [w for w in workers if not w.is_alive()]
                        if dead:
                            workers, respawns = self._respawn_or_raise(
                                workers, dead, respawns, ctx, bfn,
                                key_q, data_q, inflight)
                        if stall_limit > 0 and waited >= stall_limit:
                            raise RuntimeError(
                                f"DataLoader workers produced no batch for "
                                f"{waited:.0f}s — likely a jax/XLA call "
                                "deadlocked inside a forked worker (keep "
                                "transforms numpy-only, or use "
                                "thread_pool=True). Override with the "
                                "dataloader_timeout config option "
                                "(MXNET_TPU_DATALOADER_TIMEOUT)."
                            ) from None
                if idx not in inflight:
                    continue    # duplicate of work re-enqueued at a respawn
                inflight.pop(idx)
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                buf[idx] = batch
                _send()
        finally:
            for _ in workers:
                key_q.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
