"""DataLoader (reference: `python/mxnet/gluon/data/dataloader.py`).

The reference forks `num_workers` Python processes with shared-memory NDArray
return. TPU-native: decode/augment is host CPU work feeding one device queue,
so we use a thread pool (numpy releases the GIL for the heavy parts) plus a
double-buffered prefetcher — the same structure as the reference's
`PrefetcherIter` (`src/io/iter_prefetcher.h`) without the process boundary.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...ndarray import ndarray as _nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    if isinstance(data[0], NDArray):
        return _nd.array(np.stack([d.asnumpy() for d in data]))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return _nd.array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch if prefetch is not None else 2 * max(num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        # threaded prefetch pipeline
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = queue.Queue()
            batches = iter(self._batch_sampler)
            stop = object()

            def submitter():
                for indices in batches:
                    futures.put(pool.submit(self._load_batch, indices))
                futures.put(stop)

            t = threading.Thread(target=submitter, daemon=True)
            t.start()
            while True:
                fut = futures.get()
                if fut is stop:
                    break
                yield fut.result()
            t.join()
