"""Samplers (reference: `python/mxnet/gluon/data/sampler.py`)."""
from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "ShardedSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(np.random.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class ShardedSampler(Sampler):
    """Each distributed worker samples a disjoint slice of the dataset;
    slices union to exactly one epoch (the DataLoader analog of the
    iterators' num_parts/part_index — reference: the partition params of
    `src/io/iter_image_recordio_2.cc`). num_parts/part_index default to the
    running multi-host job (`parallel.num_workers()`/`parallel.rank()`), so
    `DataLoader(ds, sampler=ShardedSampler(len(ds)))` is input-correct on
    every host of a launch.py job with no further wiring."""

    def __init__(self, length, num_parts=None, part_index=None, shuffle=True):
        from ...base import part_range
        if num_parts is None or part_index is None:
            from ...parallel.distributed import rank, num_workers
            num_parts = num_workers() if num_parts is None else num_parts
            part_index = rank() if part_index is None else part_index
        self._lo, self._hi = part_range(length, num_parts, part_index)
        self._shuffle = shuffle

    def __iter__(self):
        idx = np.arange(self._lo, self._hi)
        if self._shuffle:
            np.random.shuffle(idx)
        return iter(idx.tolist())

    def __len__(self):
        return self._hi - self._lo


class BatchSampler(Sampler):
    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                pass
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(self._last_batch)

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        return (len(self._sampler) + len(self._prev)) // self._batch_size
