"""Block / HybridBlock.

Reference: `python/mxnet/gluon/block.py`. The reference's `hybridize()` traces
Python forward into an NNVM graph executed by `CachedOp`
(`src/imperative/cached_op.cc`); here `hybridize()` builds a **shape-keyed
`jax.jit` cache**: one fused XLA computation per (input shapes/dtypes,
train-flag) key — the whole block becomes a single device program, which is
the TPU-idiomatic replacement for both GraphExecutor and CachedOp
(SURVEY.md §7.1).

Functionalization: under trace, each Parameter's buffer is temporarily
rebound to a tracer, the user's `hybrid_forward` runs unchanged, and aux
state (e.g. BatchNorm running stats, grad_req='null') is harvested as extra
outputs then written back eagerly after the compiled call — so mutable-state
semantics survive jit.
"""
from __future__ import annotations

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import _engine
from .. import check as _check
from .. import diagnostics as _diagnostics
from .. import inspect as _inspect
from .. import memsafe as _memsafe
from .. import ndarray as nd_mod
from .. import random as _random
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "Sequential", "HybridSequential",
           "functional_call"]

_M_CACHE_HITS = _telemetry.counter(
    "hybrid_cache_hits_total", "jit-cache hits across all HybridBlocks")
_M_CACHE_MISSES = _telemetry.counter(
    "hybrid_cache_misses_total", "jit-cache misses (each one is a trace+compile)")
_M_COMPILES = _telemetry.counter(
    "compile_total", "XLA compilations (HybridBlock cache + sharded step cache)")
_M_RECOMPILES = _telemetry.counter(
    "recompile_total", "compilations after the first for the same block/step "
    "(shape/dtype churn — the silent throughput killer)")
_M_COMPILE_SECONDS = _telemetry.histogram(
    "compile_seconds", "wall-clock trace+compile time (includes the first "
    "execution of the jitted program, which XLA compiles lazily)")


class Block:
    """Base neural-network building block (imperative)."""

    def __init__(self, prefix=None, params=None):
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []
        self.prefix = prefix or ""

    # -- attribute registration ----------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.__dict__.setdefault("_children", {})[name] = value
        elif isinstance(value, Parameter):
            self.__dict__.setdefault("_reg_params", {})[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        name = name or str(len(self._children))
        self._children[name] = block
        return block

    @property
    def params(self):
        d = ParameterDict()
        for name, p in self._reg_params.items():
            d[name] = p
        return d

    def collect_params(self, select=None):
        """All parameters in this subtree, keyed by dotted path."""
        import re
        out = ParameterDict()
        for path, p in self._iter_params():
            if select is None or re.search(select, path):
                out[path] = p
        return out

    def _iter_params(self, prefix=""):
        for name, p in self._reg_params.items():
            yield prefix + name, p
        for cname, child in self._children.items():
            yield from child._iter_params(prefix + cname + ".")

    @contextlib.contextmanager
    def name_scope(self):
        """Kept for reference API compatibility; naming is attribute-path based."""
        yield self

    # -- lifecycle ------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for _, p in self._iter_params():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def cast(self, dtype):
        for _, p in self._iter_params():
            p.cast(dtype)
        for child in self._children.values():
            pass  # params already covered by _iter_params
        self._clear_cache()
        return self

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def _clear_cache(self):
        pass

    def save_parameters(self, filename, deduplicate=False):
        self.collect_params().save(filename)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        self.collect_params().load(filename, ctx=ctx, allow_missing=allow_missing,
                                   ignore_extra=ignore_extra)

    def summary(self, *inputs):
        """Print a per-layer table of output shapes and parameter counts
        for one forward pass (reference: Block.summary, gluon 1.3+).

        Must be called BEFORE hybridize(): the cached-jit path bypasses
        forward hooks, so a hybridized forward would record no layers
        (the reference asserts the same)."""
        def any_active(blk):
            if getattr(blk, "_active", False):
                return True
            return any(any_active(c) for c in blk._children.values())

        if any_active(self):
            raise ValueError(
                "summary() needs the eager forward; call it before "
                "hybridize() (or after hybridize(active=False))")
        rows = []
        hooks = []

        def install(block, path):
            def hook(blk, ins, out, _path=path):
                outs = out if isinstance(out, (list, tuple)) else [out]
                shape = ", ".join(str(tuple(o.shape)) for o in outs
                                  if hasattr(o, "shape"))
                n_params = sum(
                    int(np.prod(p.shape)) for _, p in blk._reg_params.items()
                    if p.shape is not None)
                rows.append((f"{_path}({type(blk).__name__})", shape,
                             n_params))
            block.register_forward_hook(hook)
            hooks.append((block, hook))
            for cname, child in block._children.items():
                install(child, f"{path}.{cname}" if path else cname)

        install(self, "")
        try:
            self(*inputs)
        finally:
            for blk, handle in hooks:
                if handle in blk._forward_hooks:
                    blk._forward_hooks.remove(handle)
        total = sum(int(np.prod(p.shape)) for _, p in self._iter_params()
                    if p.shape is not None)
        trainable = sum(
            int(np.prod(p.shape)) for _, p in self._iter_params()
            if p.shape is not None and p.grad_req != "null")
        width = max([len(r[0]) for r in rows] + [20])
        lines = ["-" * (width + 40),
                 f"{'Layer (type)':<{width}}  {'Output Shape':<24} Param #",
                 "=" * (width + 40)]
        for name, shape, n in rows:
            lines.append(f"{name:<{width}}  {shape:<24} {n}")
        lines += ["=" * (width + 40),
                  f"Total params: {total}",
                  f"Trainable params: {trainable}",
                  f"Non-trainable params: {total - trainable}",
                  "-" * (width + 40)]
        text = "\n".join(lines)
        print(text)
        return text

    # -- hooks ----------------------------------------------------------
    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    # -- call path ------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, child in self._children.items():
            body = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {body}")
        lines.append(")")
        return "\n".join(lines)


class HybridBlock(Block):
    """Block that can be compiled to one XLA computation per input signature."""

    #: blocks that consume remat policies STRUCTURALLY (per-layer / scan-body
    #: jax.checkpoint — models.BERTModel / models.GPTModel) set this True;
    #: remat() then routes the policy to them instead of wrapping the whole
    #: pure function
    _remat_handles_policy = False

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cache = {}
        self._tele_sig = None     # last compiled input signature (telemetry)

    def remat(self, policy="layers"):
        """Set this block tree's rematerialization policy (mx.memsafe
        graduated remat): "none" | "dots_saveable" | "layers" | "full",
        in increasing memory savings / recompute cost, mapped onto
        jax.checkpoint. Blocks with structural layer handling (BERTModel,
        GPTModel) checkpoint per layer / per scan body; any other block
        gets the policy applied around its whole compiled function.
        Replaces the ad-hoc per-model `remat=` boolean (which keeps
        working as the "layers" alias). Clears compiled caches so the
        next call re-traces under the new policy. Returns self."""
        _memsafe.validate_policy(policy)
        self._propagate_remat(policy)
        self._remat_policy = policy
        # bumped on every policy change: a ShardedTrainer keys its step
        # cache on this, so remat() mid-run re-jits there too (clearing
        # our own _cache cannot reach the trainer's executables)
        self._remat_epoch = getattr(self, "_remat_epoch", 0) + 1
        self._clear_cache()
        return self

    def _propagate_remat(self, policy):
        handled = False
        if type(self)._remat_handles_policy:
            self._remat_policy = policy
            handled = True
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                handled = child._propagate_remat(policy) or handled
        return handled

    def hybridize(self, active=True, static_alloc=False, static_shape=False, **kwargs):
        self._active = active
        self._cache = {}
        super().hybridize(active, **kwargs)

    def _clear_cache(self):
        self._cache = {}
        for child in self._children.values():
            child._clear_cache()

    def infer_shape(self, *args):
        """Run deferred-shape resolution without compiling (eager pass)."""
        self.forward(*args)

    # -- eager path: hybrid_forward with params as kwargs ----------------
    def forward(self, *args, **kwargs):
        pkwargs = {}
        for name, p in self._reg_params.items():
            try:
                pkwargs[name] = p.data()
            except DeferredInitializationError:
                self._deferred_infer_shape(name, p, args)
                pkwargs[name] = p.data()
        return self.hybrid_forward(nd_mod, *args, **pkwargs, **kwargs)

    def _deferred_infer_shape(self, name, param, args):
        """Layers override `infer_param_shapes` to complete deferred dims."""
        shapes = self.infer_param_shapes(
            *[a.shape if isinstance(a, NDArray) else None for a in args])
        if name not in shapes:
            raise DeferredInitializationError(
                f"cannot infer shape of parameter '{name}'")
        param._finish_deferred_init(shapes[name])

    def infer_param_shapes(self, *in_shapes):
        raise DeferredInitializationError(
            f"{type(self).__name__} does not support deferred init")

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError

    # -- compiled path ---------------------------------------------------
    def __call__(self, *args, **kwargs):
        if not self._active or kwargs or not all(isinstance(a, NDArray) for a in args):
            return super().__call__(*args, **kwargs)
        try:
            return self._call_cached(args)
        except DeferredInitializationError:
            # first call resolves deferred shapes eagerly (reference behavior)
            return super().__call__(*args)

    def _param_lists(self):
        grad_params, aux_params = [], []
        for path, p in self._iter_params():
            d = p.data()  # raises DeferredInitializationError if not ready
            if p.grad_req == "null":
                aux_params.append((path, p))
            else:
                grad_params.append((path, p))
        return grad_params, aux_params

    def _call_cached(self, args):
        grad_params, aux_params = self._param_lists()
        train = _engine.is_training()
        key = (tuple((a.shape, str(a.dtype)) for a in args), train,
               len(grad_params), len(aux_params))
        entry = self._cache.get(key)
        is_miss = entry is None
        t0 = time.perf_counter() if (
            is_miss and (_telemetry._enabled or _diagnostics._enabled
                         or _trace._enabled)) \
            else None
        if is_miss:
            entry = self._build_cached(args, grad_params, aux_params, train)
            self._cache[key] = entry
        jitted, out_treedef = entry

        gp_data = [p.data()._data for _, p in grad_params]
        aux_data = [p.data()._data for _, p in aux_params]
        in_data = [a._data for a in args]
        rng = _random.next_key()

        prefl = None
        if is_miss and (_memsafe._enabled or _check._enabled) and not any(
                isinstance(d, jax.core.Tracer) for d in in_data):
            # pre-dispatch analyses for the fresh executable. Child
            # blocks compiling inside a parent trace (tracer inputs) are
            # the parent executable's problem, not their own. When BOTH
            # subsystems are on, the computation is traced ONCE and
            # shared: check lints the jaxpr, memsafe lowers the same
            # trace for its analysis compile
            hook_args = (gp_data, aux_data, rng) + tuple(in_data)
            traced = _check.trace_jit(jitted, hook_args) \
                if (_check._enabled and _memsafe._enabled) else None
            if _memsafe._enabled:
                # pre-flight budget check BEFORE the first dispatch: AOT
                # lower+compile (warm via compile_cache_dir for the real
                # call below) and compare predicted peak + resident
                # params/inputs against device capacity — a predicted
                # overrun raises MemoryBudgetError with nothing dispatched
                try:
                    prefl = _memsafe.preflight_jit(
                        type(self).__name__, key, jitted, hook_args,
                        traced=traced)
                except _memsafe.MemoryBudgetError:
                    # a rejected executable must not stay cached: a
                    # retried call would hit the cache and dispatch past
                    # the check
                    self._cache.pop(key, None)
                    raise
            if _check._enabled:
                # mx.check graph lint (trace-only — no compile): large
                # baked constants, silent dtype promotions, retrace
                # hazards
                try:
                    _check.check_jit(type(self).__name__, key, jitted,
                                     hook_args,
                                     owner=_check.owner_token(self),
                                     traced=traced)
                except _check.CheckError:
                    # check=error: a rejected executable must not stay
                    # cached (a retry would hit the cache, skip the lint)
                    self._cache.pop(key, None)
                    raise

        # the first call of a fresh entry triggers XLA's lazy compile, so
        # the compile-time measurement must bracket it
        out_flat, new_aux = jitted(gp_data, aux_data, rng, *in_data)
        if t0 is not None:
            dt = time.perf_counter() - t0
            if _telemetry._enabled:
                self._tele_record_compile(args, train, dt,
                                          len(grad_params), len(aux_params))
            if _diagnostics._enabled:
                # compile events land in the flight-recorder ring too: a
                # post-mortem showing recompiles right before the crash is
                # the shape-churn smoking gun
                _diagnostics.record_event(
                    "compile", block=type(self).__name__,
                    compile_time_s=round(dt, 6),
                    shapes=[list(a.shape) for a in args])
            if _trace._enabled:
                # every compile is a span (always=True: compiles are rare
                # and seconds-scale — sampling away the exact event a
                # trace exists to show would be self-defeating)
                _trace.record_span("compile", t0, t0 + dt, cat="compile",
                                   always=True, block=type(self).__name__)
        elif _telemetry._enabled and not is_miss:
            _M_CACHE_HITS.inc()
        if is_miss and _inspect._enabled \
                and not (prefl and prefl.get("inspect_recorded")) \
                and not any(
                isinstance(d, jax.core.Tracer) for d in in_data):
            # cost attribution for the freshly built executable: one extra
            # lower+compile at the same signature. Runs AFTER the measured
            # first call and its telemetry/ring records so the analysis
            # compile neither inflates compile_seconds nor steals the
            # persistent-cache cold miss (it is served warm from the real
            # compile when compile_cache_dir is set). A child block
            # compiling INSIDE a parent trace (tracer inputs) is skipped —
            # the parent's executable subsumes its cost
            _inspect.analyze_jit(type(self).__name__, _inspect.key_repr(key),
                                 jitted, gp_data, aux_data, rng, *in_data)
        for (_, p), v in zip(aux_params, new_aux):
            p.data()._data = v

        outs = [NDArray(o) for o in out_flat]
        if _engine.is_recording():
            def record_fn(*arrs, _n=len(gp_data)):
                o, _ = jitted(list(arrs[:_n]), aux_data, rng, *arrs[_n:])
                return tuple(o)
            parents = [("leaf", p.data()) for _, p in grad_params]
            for a in args:
                if a._node is not None:
                    parents.append(("node",) + a._node)
                else:
                    parents.append(("leaf", a))
            _engine.record_op(record_fn, tuple(gp_data) + tuple(in_data),
                              parents, outs)
        return jax.tree.unflatten(out_treedef, outs)

    def _tele_record_compile(self, args, train, dt, n_grad, n_aux):
        """One jit-cache miss: count it, time it, and diagnose WHY by
        diffing the input signature against the previous compile's. n_grad
        and n_aux are part of the cache key (freezing a layer recompiles),
        so they belong in the signature — without them that recompile would
        be misdiagnosed as 'signature unchanged'."""
        _M_CACHE_MISSES.inc()
        _M_COMPILES.inc()
        _M_COMPILE_SECONDS.observe(dt)
        sig = _telemetry.signature(args, train=train,
                                   n_grad=n_grad, n_aux=n_aux)
        causes, changed = _telemetry.diff_signature(self._tele_sig, sig)
        kind = "compile" if self._tele_sig is None else "recompile"
        if self._tele_sig is not None:
            _M_RECOMPILES.inc()
        self._tele_sig = sig
        _telemetry.event(kind, block=type(self).__name__,
                         compile_time_s=round(dt, 6), causes=causes,
                         changed=changed, signature=sig)

    def _build_cached(self, args, grad_params, aux_params, train):
        """Trace self.forward into one jitted function (the CachedOp build)."""
        pure, treedef_box = _make_pure_fn(self, grad_params, aux_params, train)
        # abstract probe run: fills treedef_box, validates shapes, no compile
        jax.eval_shape(pure,
                       [p.data()._data for _, p in grad_params],
                       [p.data()._data for _, p in aux_params],
                       jax.random.key(0),
                       *[a._data for a in args])
        return jax.jit(pure), treedef_box["td"]

    def export(self, path, epoch=0):
        """Serialize params (graph export is subsumed by jit re-trace on load;
        reference: `HybridBlock.export` symbol-json + params)."""
        self.save_parameters(f"{path}-{epoch:04d}.params")


def _make_pure_fn(block, grad_params, aux_params, train):
    """Pure jax function of a Block's forward by parameter functionalization:
    `fn(gp_data, aux_data, rng, *in_data) -> (out_data_list, new_aux_list)`.

    Shared by the hybridize cache and the sharded train-step builder
    (mxnet_tpu.parallel) — the same trace that replaces the reference's
    CachedOp also feeds pjit over a device mesh."""
    treedef_box = {}

    def run(gp_data, aux_data, rng, *in_data):
        saved = []
        for (_, p), d in list(zip(grad_params, gp_data)) + list(zip(aux_params, aux_data)):
            saved.append((p, p._data._data))
            p._data._data = d
        prev_rec = _engine.set_recording(False)
        prev_train = _engine.set_training(train)
        try:
            with _random.key_scope(rng):
                out = block.forward(*[NDArray(d) for d in in_data])
            new_aux = [p._data._data for _, p in aux_params]
        finally:
            _engine.set_recording(prev_rec)
            _engine.set_training(prev_train)
            for p, orig in saved:
                p._data._data = orig
        out_flat, treedef = jax.tree.flatten(
            out, is_leaf=lambda x: isinstance(x, NDArray))
        treedef_box["td"] = treedef
        out_data = [o._data if isinstance(o, NDArray) else jnp.asarray(o)
                    for o in out_flat]
        return out_data, new_aux

    def pure(gp_data, aux_data, rng, *in_data):
        # graduated remat for blocks WITHOUT structural layer handling:
        # the whole functionalized forward under jax.checkpoint — the
        # backward (ShardedTrainer grad, autograd record_fn) recomputes
        # per the policy. Resolved at trace time so remat()/knob changes
        # take effect on the next (cache-cleared) compile.
        policy = _memsafe.block_wrap_policy(block)
        if policy is None:
            return run(gp_data, aux_data, rng, *in_data)
        wrapped = jax.checkpoint(run, policy=_memsafe.jax_policy(policy))
        return wrapped(gp_data, aux_data, rng, *in_data)

    return pure, treedef_box


def functional_call(block, train=True):
    """Public functionalization hook: returns (fn, grad_params, aux_params)
    where fn(gp_data, aux_data, rng, *inputs) -> (outputs, new_aux) is pure."""
    grad_params, aux_params = block._param_lists()
    pure, _ = _make_pure_fn(block, grad_params, aux_params, train)
    return pure, grad_params, aux_params


class Sequential(Block):
    """Imperative container (reference: gluon.nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __iter__(self):
        return iter(self._children.values())

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        return list(self._children.values())[idx]


class HybridSequential(HybridBlock):
    """Hybridizable container (reference: gluon.nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        # containers don't have own params; route through children directly
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __iter__(self):
        return iter(self._children.values())

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        return list(self._children.values())[idx]
