"""Vision model zoo (reference: `python/mxnet/gluon/model_zoo/vision/` —
alexnet/vgg/resnet/squeezenet/mobilenet/densenet + `get_model` registry).

All nets are plain gluon HybridBlocks; `net.hybridize()` compiles each to a
single XLA computation. `pretrained=True` loads `.params` files from
`root` (no network access in this environment — weights must be placed
there by the user; the reference downloaded them from its model store).

ResNets delegate to `mxnet_tpu.models.resnet` (the benchmark family).
"""
from __future__ import annotations

import os

from .. import nn
from ..block import HybridBlock

__all__ = ["get_model", "alexnet", "vgg11", "vgg13", "vgg16", "vgg19",
           "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "squeezenet1_0",
           "squeezenet1_1", "mobilenet1_0", "mobilenet0_5", "mobilenet0_25",
           "mobilenet_v2_1_0", "mobilenet_v2_0_5", "resnet18_v1",
           "resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
           "resnet18_v2", "resnet34_v2", "resnet50_v2", "resnet101_v2",
           "resnet152_v2",
           "densenet121", "densenet161", "densenet169", "densenet201",
           "inception_v3",
           "AlexNet", "VGG", "SqueezeNet", "MobileNet", "MobileNetV2",
           "DenseNet", "Inception3"]


def _load_pretrained(net, name, root):
    path = os.path.join(os.path.expanduser(root), f"{name}.params")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"pretrained weights for {name!r} not found at {path}; this "
            f"environment has no model store access — place a .params file "
            f"there (reference format, nd.save dict)")
    net.load_parameters(path)


class AlexNet(HybridBlock):
    """Reference: model_zoo/vision/alexnet.py."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        for args in [(64, 11, 4, 2), (192, 5, 1, 2)]:
            ch, k, s, p = args
            self.features.add(nn.Conv2D(ch, k, strides=s, padding=p,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
        for ch in (384, 256):
            self.features.add(nn.Conv2D(ch, 3, padding=1, activation="relu"))
        self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


_VGG_SPEC = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
             13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
             16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
             19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


class VGG(HybridBlock):
    """Reference: model_zoo/vision/vgg.py."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        for num, ch in zip(layers, filters):
            for _ in range(num):
                self.features.add(nn.Conv2D(ch, 3, padding=1, use_bias=True))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2))
        self.features.add(nn.Flatten())
        for _ in range(2):
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
        self.expand1 = nn.Conv2D(expand1x1, 1, activation="relu")
        self.expand3 = nn.Conv2D(expand3x3, 3, padding=1, activation="relu")

    def forward(self, x):
        from ... import nd
        x = self.squeeze(x)
        return nd.concat(self.expand1(x), self.expand3(x), dim=1)


class SqueezeNet(HybridBlock):
    """Reference: model_zoo/vision/squeezenet.py."""

    def __init__(self, version="1.0", classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in ("1.0", "1.1"):
            raise ValueError(f"unsupported SqueezeNet version {version!r}; "
                             f"choose '1.0' or '1.1'")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, strides=2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(16, 64, 64), (16, 64, 64), (32, 128, 128)]:
                self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(32, 128, 128), (48, 192, 192),
                               (48, 192, 192), (64, 256, 256)]:
                self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(64, 256, 256))
        else:  # 1.1
            self.features.add(nn.Conv2D(64, 3, strides=2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(16, 64, 64), (16, 64, 64)]:
                self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(32, 128, 128), (32, 128, 128)]:
                self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(48, 192, 192), (48, 192, 192),
                               (64, 256, 256), (64, 256, 256)]:
                self.features.add(_Fire(sq, e1, e3))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def _conv_bn_relu(seq, channels, kernel, stride=1, pad=0, groups=1,
                  relu6=False):
    seq.add(nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                      groups=groups, use_bias=False))
    seq.add(nn.BatchNorm())
    seq.add(nn.Activation("relu6" if relu6 else "relu"))


class MobileNet(HybridBlock):
    """Depthwise-separable MobileNet v1 (reference: mobilenet.py).
    Depthwise = grouped conv with groups == channels — XLA lowers this to
    a feature-group convolution the TPU handles natively."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        def c(ch):
            return max(int(ch * multiplier), 8)
        spec = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
                (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
                (1024, 1)]
        self.features = nn.HybridSequential()
        _conv_bn_relu(self.features, c(32), 3, stride=2, pad=1)
        in_ch = c(32)
        for ch, stride in spec:
            _conv_bn_relu(self.features, in_ch, 3, stride=stride, pad=1,
                          groups=in_ch)  # depthwise
            _conv_bn_relu(self.features, c(ch), 1)  # pointwise
            in_ch = c(ch)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _InvertedResidual(HybridBlock):
    def __init__(self, in_ch, out_ch, stride, expand, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_ch == out_ch
        mid = in_ch * expand
        self.body = nn.HybridSequential()
        if expand != 1:
            _conv_bn_relu(self.body, mid, 1, relu6=True)
        _conv_bn_relu(self.body, mid, 3, stride=stride, pad=1, groups=mid,
                      relu6=True)
        self.body.add(nn.Conv2D(out_ch, 1, use_bias=False))
        self.body.add(nn.BatchNorm())

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_shortcut else out


class MobileNetV2(HybridBlock):
    """Reference: mobilenet.py MobileNetV2 (inverted residuals)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        def c(ch):
            return max(int(ch * multiplier), 8)
        self.features = nn.HybridSequential()
        _conv_bn_relu(self.features, c(32), 3, stride=2, pad=1, relu6=True)
        in_ch = c(32)
        spec = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
                (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        for expand, ch, n, s in spec:
            for i in range(n):
                self.features.add(_InvertedResidual(
                    in_ch, c(ch), s if i == 0 else 1, expand))
                in_ch = c(ch)
        last = c(1280) if multiplier > 1.0 else 1280
        _conv_bn_relu(self.features, last, 1, relu6=True)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _DenseLayer(HybridBlock):
    """BN-ReLU-Conv1x1(4k) -> BN-ReLU-Conv3x3(k), output concatenated onto
    the input (reference model_zoo/vision/densenet.py _make_dense_layer)."""

    def __init__(self, growth_rate, bn_size=4, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(bn_size * growth_rate, 1, use_bias=False),
                      nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(growth_rate, 3, padding=1, use_bias=False))
        self._dropout = dropout
        if dropout:
            self.drop = nn.Dropout(dropout)

    def forward(self, x):
        from ... import nd
        out = self.body(x)
        if self._dropout:
            out = self.drop(out)
        return nd.concat(x, out, dim=1)


class _Transition(HybridBlock):
    def __init__(self, out_channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(out_channels, 1, use_bias=False),
                      nn.AvgPool2D(2, 2))

    def forward(self, x):
        return self.body(x)


_DENSENET_SPEC = {121: (64, 32, [6, 12, 24, 16]),
                  161: (96, 48, [6, 12, 36, 24]),
                  169: (64, 32, [6, 12, 32, 32]),
                  201: (64, 32, [6, 12, 48, 32])}


class DenseNet(HybridBlock):
    """Reference: model_zoo/vision/densenet.py."""

    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(num_init_features, 7, strides=2,
                                    padding=3, use_bias=False),
                          nn.BatchNorm(), nn.Activation("relu"),
                          nn.MaxPool2D(3, 2, padding=1))
        channels = num_init_features
        for i, num_layers in enumerate(block_config):
            for _ in range(num_layers):
                self.features.add(_DenseLayer(growth_rate, bn_size, dropout))
            channels += num_layers * growth_rate
            if i != len(block_config) - 1:
                channels //= 2
                self.features.add(_Transition(channels))
        self.features.add(nn.BatchNorm(), nn.Activation("relu"),
                          nn.GlobalAvgPool2D(), nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _Branches(HybridBlock):
    """Parallel branches concatenated on the channel axis (the Inception
    block wiring primitive)."""

    def __init__(self, *branches):
        super().__init__()
        self.branches = nn.HybridSequential()
        for b in branches:
            self.branches.add(b)

    def forward(self, x):
        from ... import nd
        return nd.concat(*[b(x) for b in self.branches._children.values()],
                         dim=1)


def _i3_conv(ch, k, s=1, p=0):
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(ch, k, strides=s, padding=p, use_bias=False),
            nn.BatchNorm(epsilon=0.001), nn.Activation("relu"))
    return blk


def _i3_seq(*blocks):
    s = nn.HybridSequential()
    s.add(*blocks)
    return s


class Inception3(HybridBlock):
    """Inception-v3 (reference model_zoo/vision/inception.py), built from
    the standard A/B/C/D/E blocks; expects 299x299 inputs (any >= 75 works
    — the head is a global pool)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        conv, seq = _i3_conv, _i3_seq

        def pool_branch(pool, ch):
            return seq(pool, conv(ch, 1))

        def block_a(pool_ch):
            return _Branches(
                conv(64, 1),
                seq(conv(48, 1), conv(64, 5, p=2)),
                seq(conv(64, 1), conv(96, 3, p=1), conv(96, 3, p=1)),
                pool_branch(nn.AvgPool2D(3, 1, padding=1), pool_ch))

        def block_b():
            return _Branches(
                conv(384, 3, s=2),
                seq(conv(64, 1), conv(96, 3, p=1), conv(96, 3, s=2)),
                nn.MaxPool2D(3, 2))

        def block_c(ch7):
            return _Branches(
                conv(192, 1),
                seq(conv(ch7, 1), conv(ch7, (1, 7), p=(0, 3)),
                    conv(192, (7, 1), p=(3, 0))),
                seq(conv(ch7, 1), conv(ch7, (7, 1), p=(3, 0)),
                    conv(ch7, (1, 7), p=(0, 3)), conv(ch7, (7, 1), p=(3, 0)),
                    conv(192, (1, 7), p=(0, 3))),
                pool_branch(nn.AvgPool2D(3, 1, padding=1), 192))

        def block_d():
            return _Branches(
                seq(conv(192, 1), conv(320, 3, s=2)),
                seq(conv(192, 1), conv(192, (1, 7), p=(0, 3)),
                    conv(192, (7, 1), p=(3, 0)), conv(192, 3, s=2)),
                nn.MaxPool2D(3, 2))

        def block_e():
            return _Branches(
                conv(320, 1),
                seq(conv(384, 1), _Branches(conv(384, (1, 3), p=(0, 1)),
                                            conv(384, (3, 1), p=(1, 0)))),
                seq(conv(448, 1), conv(384, 3, p=1),
                    _Branches(conv(384, (1, 3), p=(0, 1)),
                              conv(384, (3, 1), p=(1, 0)))),
                pool_branch(nn.AvgPool2D(3, 1, padding=1), 192))

        self.features = nn.HybridSequential()
        self.features.add(conv(32, 3, s=2), conv(32, 3), conv(64, 3, p=1),
                          nn.MaxPool2D(3, 2), conv(80, 1), conv(192, 3),
                          nn.MaxPool2D(3, 2),
                          block_a(32), block_a(64), block_a(64),
                          block_b(),
                          block_c(128), block_c(160), block_c(160),
                          block_c(192),
                          block_d(), block_e(), block_e(),
                          nn.GlobalAvgPool2D(), nn.Dropout(0.5),
                          nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


# --------------------------------------------------------------------------
# factory functions + registry
# --------------------------------------------------------------------------

def alexnet(pretrained=False, root="~/.mxnet/models", **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        _load_pretrained(net, "alexnet", root)
    return net


def _make_vgg(num, batch_norm=False):
    def factory(pretrained=False, root="~/.mxnet/models", **kwargs):
        layers, filters = _VGG_SPEC[num]
        net = VGG(layers, filters, batch_norm=batch_norm, **kwargs)
        if pretrained:
            _load_pretrained(net, f"vgg{num}{'_bn' if batch_norm else ''}",
                             root)
        return net
    factory.__name__ = f"vgg{num}{'_bn' if batch_norm else ''}"
    return factory


vgg11, vgg13, vgg16, vgg19 = (_make_vgg(n) for n in (11, 13, 16, 19))
vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn = (
    _make_vgg(n, True) for n in (11, 13, 16, 19))


def squeezenet1_0(pretrained=False, root="~/.mxnet/models", **kwargs):
    net = SqueezeNet("1.0", **kwargs)
    if pretrained:
        _load_pretrained(net, "squeezenet1.0", root)
    return net


def squeezenet1_1(pretrained=False, root="~/.mxnet/models", **kwargs):
    net = SqueezeNet("1.1", **kwargs)
    if pretrained:
        _load_pretrained(net, "squeezenet1.1", root)
    return net


def _make_mobilenet(multiplier, v2=False):
    def factory(pretrained=False, root="~/.mxnet/models", **kwargs):
        cls = MobileNetV2 if v2 else MobileNet
        net = cls(multiplier, **kwargs)
        if pretrained:
            tag = f"mobilenetv2_{multiplier}" if v2 else \
                f"mobilenet{multiplier}"
            _load_pretrained(net, tag, root)
        return net
    factory.__name__ = (f"mobilenet_v2_{multiplier}" if v2
                        else f"mobilenet_{multiplier}").replace(".", "_")
    return factory


mobilenet1_0 = _make_mobilenet(1.0)
mobilenet0_5 = _make_mobilenet(0.5)
mobilenet0_25 = _make_mobilenet(0.25)
mobilenet_v2_1_0 = _make_mobilenet(1.0, v2=True)
mobilenet_v2_0_5 = _make_mobilenet(0.5, v2=True)


def _resnet_factory(name):
    def factory(pretrained=False, root="~/.mxnet/models", **kwargs):
        from ...models import resnet as _resnet
        net = getattr(_resnet, name)(**kwargs)
        if pretrained:
            _load_pretrained(net, name, root)
        return net
    factory.__name__ = name
    return factory


def _make_densenet(num):
    def factory(pretrained=False, root="~/.mxnet/models", **kwargs):
        init, growth, cfg = _DENSENET_SPEC[num]
        net = DenseNet(init, growth, cfg, **kwargs)
        if pretrained:
            _load_pretrained(net, f"densenet{num}", root)
        return net
    factory.__name__ = f"densenet{num}"
    return factory


densenet121 = _make_densenet(121)
densenet161 = _make_densenet(161)
densenet169 = _make_densenet(169)
densenet201 = _make_densenet(201)


def inception_v3(pretrained=False, root="~/.mxnet/models", **kwargs):
    net = Inception3(**kwargs)
    if pretrained:
        _load_pretrained(net, "inceptionv3", root)
    return net


resnet18_v1 = _resnet_factory("resnet18_v1")
resnet34_v1 = _resnet_factory("resnet34_v1")
resnet50_v1 = _resnet_factory("resnet50_v1")
resnet101_v1 = _resnet_factory("resnet101_v1")
resnet152_v1 = _resnet_factory("resnet152_v1")
resnet18_v2 = _resnet_factory("resnet18_v2")
resnet34_v2 = _resnet_factory("resnet34_v2")
resnet50_v2 = _resnet_factory("resnet50_v2")
resnet101_v2 = _resnet_factory("resnet101_v2")
resnet152_v2 = _resnet_factory("resnet152_v2")

_MODELS = {
    "alexnet": alexnet,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.5": mobilenet0_5,
    "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.5": mobilenet_v2_0_5,
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    """Fetch a model constructor by name (reference: model_zoo.get_model)."""
    name = name.lower()
    if name not in _MODELS:
        raise ValueError(f"unknown model {name!r}; available: "
                         f"{sorted(_MODELS)}")
    return _MODELS[name](**kwargs)
