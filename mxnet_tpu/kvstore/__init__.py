"""KVStore facade.

Reference: `python/mxnet/kvstore.py` over `src/kvstore/` (CommDevice P2P
reduce, NCCL rings, ps-lite parameter servers). On TPU there is no transport
to manage — XLA collectives over ICI/DCN do gradient reduction inside jitted
steps (SURVEY.md §2.5). This module keeps the *semantic* surface so reference
training scripts run unchanged:

  * push(key, value|[values]) — values are summed (the reduce the reference
    does across GPUs/workers)
  * pull(key, out|[outs]) — broadcast the stored value
  * set_optimizer / update semantics (`update_on_kvstore`) — the optimizer
    runs where the aggregate lives, as with a PS server

`dist_async` is intentionally unsupported: async parameter-server updates
have no SPMD equivalent (SURVEY.md §2.4) — sync data parallelism via the
mesh is the supported mode, matching `dist_sync` semantics.

2-bit gradient compression with error feedback IS supported
(`set_gradient_compression({'type': '2bit', 'threshold': t})`, see
compression.py) — applied on dense pushes, matching the reference's
worker-side quantize → server-sum → dequantize flow.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["KVStore", "create"]

_M_KV_CALLS = _telemetry.counter(
    "kvstore_calls_total", "KVStore data-plane calls, labelled op=push|pull")
_M_KV_BYTES = _telemetry.counter(
    "kvstore_bytes_total", "payload bytes through the KVStore data plane, "
    "labelled op=push|pull")


def _payload_bytes(values):
    """Raw payload bytes of a (possibly nested) value list. Dense NDArrays
    carry their buffer under ._data; sparse ones have _data=None and store
    value/index buffers under ._values / ._indices."""
    n = 0
    for v in values:
        for x in (v if isinstance(v, (list, tuple)) else [v]):
            if x is None:
                continue
            for buf in ("_data", "_values", "_indices"):
                d = getattr(x, buf, None)
                if d is not None and hasattr(d, "nbytes"):
                    n += int(d.nbytes)
    return n


def _tele_bytes(op, values):
    """Count one data-plane call and its payload bytes."""
    _M_KV_CALLS.labels(op=op).inc()
    _M_KV_BYTES.labels(op=op).inc(_payload_bytes(values))


def _nd_scalar(v):
    return NDArray(jnp.asarray([v], jnp.int32))


class KVStore:
    def __init__(self, kind):
        self.type = kind
        self._store = {}
        self._pending = {}
        self._opt_states = {}
        self._optimizer = None
        self._updater = None
        self._compression = None

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit gradient compression with error feedback on push
        (reference: KVStore.set_gradient_compression /
        src/kvstore/gradient_compression.cc)."""
        from . import compression as _comp
        self._compression = _comp.create(compression_params)

    # -- data plane ------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            self._store[k] = NDArray(self._first(v)._data)

    def push(self, key, value, priority=0):
        from ..ndarray.sparse import BaseSparseNDArray, add as sparse_add
        keys, values = self._normalize(key, value)
        # byte counting is per committed key — a rejected key contributes
        # nothing, but keys already applied before a later key fails DID
        # move their bytes and stay counted; the call counts iff any key
        # committed (hence the try/finally)
        pushed_any = False
        try:
            for k, v in zip(keys, values):
                vs = v if isinstance(v, (list, tuple)) else [v]
                kb = 0      # telemetry: this key's wire payload
                # validate BEFORE any aggregation: compression keeps
                # error-feedback residuals, which a failed push must not
                # touch
                if k not in self._store:
                    raise KeyError(f"key {k} not initialized")
                if any(isinstance(x, BaseSparseNDArray) for x in vs):
                    # sparse aggregate stays sparse so the optimizer can
                    # take its lazy row-update path (reference: sparse push
                    # keeps kRowSparseStorage through the server merge);
                    # compression applies to dense pushes only (reference
                    # behavior)
                    agg = vs[0]
                    for extra in vs[1:]:
                        agg = sparse_add(agg, extra)
                    if _telemetry._enabled:
                        kb = _payload_bytes(vs)
                elif self._compression is not None:
                    # per-slot quantize with error feedback (int8 wire
                    # payloads, the reference's worker->server format),
                    # aggregate in int32 so any slot count sums exactly,
                    # dequantize in the gradients' own dtype
                    qs = [self._compression.compress(k, i, x._data)
                          for i, x in enumerate(vs)]
                    if _telemetry._enabled:
                        # the quantized wire payload, not the f32 inputs —
                        # byte counts must reflect what compression saves
                        kb = sum(int(q.nbytes) for q in qs)
                    qsum = qs[0].astype(jnp.int32)
                    for q in qs[1:]:
                        qsum = qsum + q
                    agg = NDArray(self._compression.decompress(qsum)
                                  .astype(vs[0]._data.dtype))
                else:
                    agg = NDArray(sum((x._data for x in vs[1:]),
                                      vs[0]._data))
                    if _telemetry._enabled:
                        kb = _payload_bytes(vs)
                if self._updater is not None:
                    self._updater(k, agg, self._store[k])
                elif self._optimizer is not None:
                    state = self._opt_states.setdefault(
                        k, self._optimizer.create_state(k, self._store[k]))
                    self._optimizer.update(k, self._store[k], agg, state)
                else:
                    dense = agg.todense()._data \
                        if isinstance(agg, BaseSparseNDArray) else agg._data
                    self._pending[k] = self._pending.get(k, 0) + dense
                if _telemetry._enabled:
                    _M_KV_BYTES.labels(op="push").inc(kb)
                    pushed_any = True
        finally:
            if pushed_any:
                _M_KV_CALLS.labels(op="push").inc()

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        results = []
        for k, o in zip(keys, outs):
            val = self._store[k]._data
            if k in self._pending:
                val = val + self._pending.pop(k)
                self._store[k]._data = val
            if o is None:
                results.append(NDArray(val))
            else:
                from ..ndarray.sparse import BaseSparseNDArray, cast_storage
                os_ = o if isinstance(o, (list, tuple)) else [o]
                for dst in os_:
                    if isinstance(dst, BaseSparseNDArray):
                        cast_storage(NDArray(val), dst.stype).copyto(dst)
                    else:
                        dst._data = val
                results.append(o)
        if _telemetry._enabled:
            _tele_bytes("pull", results)
        return results if isinstance(key, (list, tuple)) else results[0]

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows as row_sparse (reference:
        KVStoreDist row_sparse pull of sharded embeddings)."""
        if row_ids is None:
            return self.pull(key, out, priority)
        import numpy as _np
        import jax.numpy as jnp
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(key, (list, tuple)):
            outs = out if out is not None else [None] * len(key)
            rids_list = row_ids if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(key)
            results = [self.row_sparse_pull(k, o, priority, r)
                       for k, o, r in zip(key, outs, rids_list)]
            return out if out is not None else results

        full = self.pull(key)
        rids = row_ids[0] if isinstance(row_ids, (list, tuple)) else row_ids
        rows = _np.unique(_np.asarray(rids._data
                                      if isinstance(rids, NDArray) else rids)
                          .astype(_np.int32).ravel())
        vals = full._data[jnp.asarray(rows)]
        rsp = RowSparseNDArray(vals, jnp.asarray(rows), full.shape)
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o in outs:
                rsp.copyto(o)
            return out
        return rsp

    # -- optimizer plane -------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run updates where the aggregate lives (reference:
        `update_on_kvstore=True`, optimizer pickled to PS servers)."""
        self._optimizer = optimizer
        self._opt_states = {}

    def _set_updater(self, updater):
        self._updater = updater

    # -- cluster facts ---------------------------------------------------
    @property
    def rank(self):
        import jax
        return jax.process_index()

    @property
    def num_workers(self):
        import jax
        return jax.process_count()

    def barrier(self):
        pass  # single-controller SPMD: jit dispatch is globally ordered

    def save_optimizer_states(self, fname, dump_optimizer=False):
        from ..ndarray import ndarray as _nd
        flat = {}
        for k, st in getattr(self, "_opt_states", {}).items():
            if st is None:
                continue
            # 'i:'/'s:' key-type tag: flat names are strings, but kvstore
            # keys may be ints — without the tag a resumed push(0, ...)
            # would miss _opt_states['0'] and silently reset the moments
            kk = f"{'i' if isinstance(k, int) else 's'}:{k}"
            if isinstance(st, tuple):
                # record tuple arity so None holes (e.g. multi-precision
                # SGD's (None, w32)) survive the flat round-trip
                flat[f"{kk}.__arity__"] = _nd_scalar(len(st))
                for j, s in enumerate(st):
                    if s is not None:
                        flat[f"{kk}.{j}"] = s
            else:
                flat[f"{kk}.0"] = st
        _nd.save(fname, flat)

    def load_optimizer_states(self, fname):
        """Restore save_optimizer_states output (reference:
        KVStore.load_optimizer_states / Module resume path). Flat
        '{key}.{j}' entries are regrouped; '{key}.__arity__' restores
        tuple structure including None holes; a lone '.0' without arity
        restores a bare (non-tuple) state matching create_state's shape."""
        from ..ndarray import ndarray as _nd
        if self._optimizer is None:
            raise RuntimeError(
                "call set_optimizer before load_optimizer_states "
                "(set_optimizer resets the state table)")
        flat = _nd.load(fname)
        if not isinstance(flat, dict):
            raise ValueError(
                f"{fname} is not an optimizer-state dict checkpoint")
        grouped, arity = {}, {}
        for fk, v in flat.items():
            k, _, j = fk.rpartition(".")
            if k[:2] == "i:":
                k = int(k[2:])
            elif k[:2] == "s:":
                k = k[2:]
            if j == "__arity__":
                arity[k] = int(np.asarray(v.asnumpy()).reshape(-1)[0])
                continue
            if k == "" or not j.isdigit():
                raise ValueError(f"malformed optimizer-state key '{fk}'")
            grouped.setdefault(k, {})[int(j)] = v
        for k in set(grouped) | set(arity):
            parts = grouped.get(k, {})
            if k in arity:
                self._opt_states[k] = tuple(
                    parts.get(i) for i in range(arity[k]))
            elif len(parts) == 1 and 0 in parts:
                self._opt_states[k] = parts[0]
            else:
                raise ValueError(
                    f"optimizer-state key '{k}' has indices "
                    f"{sorted(parts)} but no arity record")

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _first(v):
        return v[0] if isinstance(v, (list, tuple)) else v

    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (list, tuple)):
            return list(key), list(value) if value is not None else [None] * len(key)
        return [key], [value]


def create(name="local"):
    name = name.lower()
    if name in ("local", "device", "nccl", "dist", "dist_sync", "dist_device_sync",
                "horovod"):
        return KVStore(name)
    if name == "dist_async":
        raise MXNetError(
            "dist_async is not supported on TPU: asynchronous parameter-server "
            "updates have no SPMD equivalent. Use dist_sync (mesh data "
            "parallelism) — see mxnet_tpu.parallel.")
    raise ValueError(f"unknown kvstore type {name}")
