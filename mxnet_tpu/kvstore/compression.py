"""2-bit gradient compression with error feedback (reference:
`src/kvstore/gradient_compression.cc` — enabled via
`kvstore.set_gradient_compression({'type': '2bit', 'threshold': t})`).

Semantics match the reference: each worker's gradient is quantized to
{-t, 0, +t} (2 bits of information per element; carried as int8 here — a
4x wire reduction vs f32, the TPU-idiomatic stand-in for the reference's
bit-packing, which XLA cannot express as a collective payload), and the
quantization error is kept in a per-(key, slot) residual that is added to
the NEXT gradient before quantizing — so nothing is lost, only delayed.

The aggregation identity `sum_i t*q_i == t * sum_i q_i` lets the sum run
on the quantized payloads; the kvstore accumulates them in int32, so any
worker count sums exactly."""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["TwoBitCompression", "create"]


class TwoBitCompression:
    def __init__(self, threshold=0.5):
        if threshold <= 0:
            raise ValueError("2bit compression threshold must be > 0")
        self.threshold = float(threshold)
        self._residual = {}

    @staticmethod
    @jax.jit
    def _quantize(g, t):
        q = jnp.where(g >= t, jnp.int8(1),
                      jnp.where(g <= -t, jnp.int8(-1), jnp.int8(0)))
        residual = g - t * q.astype(jnp.float32)
        return q, residual

    def compress(self, key, slot, grad):
        """grad: f32 jax array. Returns the int8 quantized payload; the
        residual for (key, slot) is updated in place."""
        rkey = (key, slot)
        res = self._residual.get(rkey)
        g = grad.astype(jnp.float32)
        if res is not None:
            g = g + res
        q, residual = self._quantize(g, self.threshold)
        self._residual[rkey] = residual
        return q

    def decompress(self, qsum):
        """Sum of int8 payloads -> f32 gradient sum."""
        return qsum.astype(jnp.float32) * self.threshold

    def reset(self):
        self._residual.clear()


def create(params):
    """Build a compressor from the reference's param-dict form."""
    if not params:
        return None
    kind = params.get("type", "2bit")
    if kind != "2bit":
        raise ValueError(
            f"unsupported gradient compression type {kind!r}; this build "
            "implements '2bit' (the reference's only shipped type)")
    return TwoBitCompression(float(params.get("threshold", 0.5)))
