"""Autograd recording state and tape.

The reference framework's dependency engine (`src/engine/threaded_engine.cc`)
does not exist here: jax's async dispatch plus functional purity replaces
read/write-var scheduling (SURVEY.md §7.1). What remains of the imperative
runtime (`src/imperative/imperative.cc`) is the *gradient tape*: when
`autograd.record()` is active, every eager op appends a Node capturing its
pure function and inputs; `backward()` walks the tape in reverse and chains
per-op `jax.vjp` calls.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = [
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "record_op",
    "backward",
    "Node",
]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(flag):
    st = _st()
    prev, st.recording = st.recording, flag
    return prev


def set_training(flag):
    st = _st()
    prev, st.training = st.training, flag
    return prev


class Node:
    """One recorded op application (reference: AGInfo / nnvm::Node in
    `src/imperative/imperative.cc`)."""

    __slots__ = ("fn", "in_data", "parents", "n_out", "out_avals")

    def __init__(self, fn, in_data, parents, n_out, out_avals):
        self.fn = fn                # pure: (*in_data) -> tuple of outputs
        self.in_data = in_data      # jax arrays captured at record time
        self.parents = parents      # per input: ("node", Node, out_idx) | ("leaf", NDArray) | None
        self.n_out = n_out
        self.out_avals = out_avals  # (shape, dtype) per output, for zero cotangents


def record_op(fn, in_data, parents, outputs):
    """Append an op to the tape; tag each output NDArray with its node."""
    out_avals = tuple((o.shape, o.dtype) for o in outputs)
    node = Node(fn, tuple(in_data), tuple(parents), len(outputs), out_avals)
    for i, out in enumerate(outputs):
        out._node = (node, i)
    return node


def _topo_order(roots):
    """Reverse-topological DFS over Nodes (iterative; graphs can be deep)."""
    order, seen = [], set()
    stack = [(r, False) for r in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for p in node.parents:
            if p is not None and p[0] == "node":
                stack.append((p[1], False))
    # Post-order DFS appends producers before consumers; backward iterates
    # reversed(order) so each node's cotangents are complete when visited.
    return order


def backward(arrays, head_grads=None, retain_graph=False, train_mode=True):
    """Run reverse-mode accumulation from `arrays` into leaf `.grad` buffers.

    Reference semantics: `MXAutogradBackwardEx` → `Imperative::Backward`
    (`src/imperative/imperative.cc`): seeds ones for scalar-ish heads,
    accumulates into arrays that called `attach_grad()`, honouring
    grad_req 'write'|'add'.
    """
    # Replay recorded fns under the requested mode so mode-sensitive ops
    # (Dropout, BatchNorm) differentiate the same computation they ran
    # forward (reference: MXAutogradBackwardEx train_mode flag).
    prev_train = set_training(train_mode)
    try:
        _backward_impl(arrays, head_grads, retain_graph)
    finally:
        set_training(prev_train)


def _backward_impl(arrays, head_grads, retain_graph):
    roots, seeds = [], {}
    for i, arr in enumerate(arrays):
        node_ref = getattr(arr, "_node", None)
        if node_ref is None:
            raise ValueError(
                "cannot differentiate: array is not part of a recorded graph"
            )
        node, idx = node_ref
        roots.append(node)
        if head_grads is not None and head_grads[i] is not None:
            seed = head_grads[i]
            seed = seed._data if hasattr(seed, "_data") else jnp.asarray(seed)
        else:
            seed = jnp.ones(arr.shape, dtype=arr.dtype)
        key = (id(node), idx)
        seeds[key] = seeds.get(key, 0) + seed

    # cotangent store: (id(node), out_idx) -> jax array
    cots = dict(seeds)
    nodes_by_id = {}

    order = _topo_order(roots)
    for n in order:
        nodes_by_id[id(n)] = n

    leaf_accum = {}  # id(ndarray) -> (ndarray, grad)
    for node in reversed(order):
        outs = []
        any_cot = False
        for i in range(node.n_out):
            c = cots.pop((id(node), i), None)
            if c is None:
                shape, dtype = node.out_avals[i]
                c = jnp.zeros(shape, dtype)
            else:
                any_cot = True
            outs.append(c)
        if not any_cot:
            continue
        # Chain rule for this op: vjp of its pure function.
        diff_pos = [
            i for i, p in enumerate(node.parents)
            if p is not None and jnp.issubdtype(jnp.asarray(node.in_data[i]).dtype, jnp.inexact)
        ]
        if not diff_pos:
            continue

        def partial_fn(*diff_args, _node=node, _pos=tuple(diff_pos)):
            full = list(_node.in_data)
            for p, a in zip(_pos, diff_args):
                full[p] = a
            out = _node.fn(*full)
            return out if isinstance(out, tuple) else (out,)

        primals = tuple(node.in_data[i] for i in diff_pos)
        _, vjp_fn = jax.vjp(partial_fn, *primals)
        in_grads = vjp_fn(tuple(outs))
        for pos, g in zip(diff_pos, in_grads):
            parent = node.parents[pos]
            if parent is None:
                continue
            kind = parent[0]
            if kind == "node":
                _, pnode, pidx = parent
                key = (id(pnode), pidx)
                cots[key] = (cots[key] + g) if key in cots else g
            elif kind == "leaf":
                leaf = parent[1]
                k = id(leaf)
                if k in leaf_accum:
                    leaf_accum[k] = (leaf, leaf_accum[k][1] + g)
                else:
                    leaf_accum[k] = (leaf, g)

    for leaf, g in leaf_accum.values():
        if leaf.grad_req == "null" or leaf._grad is None:
            continue
        if leaf.grad_req == "add":
            leaf._grad._data = leaf._grad._data + g.astype(leaf._grad.dtype)
        else:  # 'write'
            leaf._grad._data = g.astype(leaf._grad.dtype)

    if not retain_graph:
        for arr in arrays:
            arr._node = None
