"""mx.diagnostics — flight recorder, hang/NaN watchdog, and crash post-mortem.

`mx.telemetry` answers "how fast is this run" while it is healthy; this
module answers "why did it die". A hung collective, a NaN loss at step 40k,
or a device OOM normally leaves nothing but a truncated log — fatal for a
framework meant to run production training jobs. Four pieces:

  * **flight recorder** — a bounded ring buffer of the last N step records
    (step id, loss, lr, grad-norm, input-shapes signature, key telemetry
    counters, active scope). Cheap enough to leave on: one deque append per
    step, no locks on the hot path.
  * **watchdog** — a daemon thread that fires when no step completes within
    `watchdog_deadline_s`, naming the last-entered scope ("stuck in
    sharded_step(psum) @ step 1203"), dumping all-thread stacks and a
    post-mortem. One fire per stall; re-arms on the next completed step.
  * **NaN/Inf sentinel** — opt-in (`nan_sentinel`) finiteness check on
    loss / grad-norm in the trainers; a non-finite value triggers a
    post-mortem dump and raises `NonFiniteError` instead of letting the
    run silently corrupt itself.
  * **post-mortem writer** — `faulthandler` + `sys.excepthook` + `atexit`
    integration that dumps ring buffer, telemetry registry, config
    snapshot, device-memory watermarks, and the tail of the chrome-trace
    event buffer to `diagnostics_dir/<rank>/postmortem.json` (merged
    across ranks by `tools/postmortem_report.py`).

Cost model: DISABLED (the default) is the production fast path — every
entry point checks one module-level bool and returns; no ring allocation,
no watchdog thread, no locks (`ci/run.sh sanity` asserts this). Enable
with `mx.diagnostics.install()` / `MXNET_TPU_DIAGNOSTICS=1`.

Note: `postmortem.json` is written with Python's JSON dialect (bare NaN /
Infinity literals allowed) so a non-finite watermark can never lose the
dump; `json.load` reads it back.
"""
from __future__ import annotations

import atexit
import collections
import faulthandler
import json
import math
import os
import sys
import threading
import time
import traceback

from . import _locklint
from . import config
from . import telemetry as _telemetry

__all__ = [
    "enable", "disable", "enabled", "reset", "install", "uninstall",
    "record_step", "record_event", "annotate_step", "records",
    "ring_tail", "scope",
    "Watchdog", "arm_watchdog", "disarm_watchdog", "notify_progress",
    "suspend_watchdog",
    "NonFiniteError", "sentinel_check", "grad_global_norm",
    "memory_watermarks", "dump", "postmortem_path",
]

_lock = _locklint.make_rlock("diagnostics.ring")
_enabled = False                  # the fast-path bool; see enable()/disable()
_ring = None                      # deque(maxlen=ring_size); None while disabled
_installed = False
_prev_excepthook = None
_atexit_registered = False
_dump_history = []                # (reason, ts) of every dump this process
_dir_override = None              # install(diagnostics_dir=...) argument
_rank_override = None
_faulthandler_file = None         # kept referenced so GC can't close it
_watchdog = None
_current_scope = ("", 0.0, None)  # (name, entered_at_monotonic, step)
_last_mem_sample = 0.0
_MEM_SAMPLE_INTERVAL = 1.0        # seconds between device memory_stats polls


class NonFiniteError(FloatingPointError):
    """Raised by the NaN/Inf sentinel after writing a post-mortem dump."""


# shared framework-wide series, hoisted so the per-step ring digest reads
# bare floats instead of going through the registry lock each step
_M_COMPILE_TOTAL = _telemetry.counter("compile_total")
_M_RECOMPILE_TOTAL = _telemetry.counter("recompile_total")


def enabled():
    """True when the flight recorder is on (hot paths read the module
    global `_enabled` directly — this accessor is the public spelling)."""
    return _enabled


def enable(ring_size=None):
    """Turn the flight recorder on (allocates the ring buffer)."""
    global _enabled, _ring
    with _lock:
        size = int(ring_size or config.get("diagnostics_ring_size"))
        if _ring is None or _ring.maxlen != size:
            _ring = collections.deque(_ring or (), maxlen=size)
        _enabled = True


def disable():
    """Stop recording. The ring survives for inspection; reset() drops it."""
    global _enabled
    _enabled = False


def reset():
    """Drop recorded state (tests and run boundaries). While disabled the
    ring itself is released, restoring the zero-allocation fast path."""
    global _ring
    with _lock:
        if _ring is not None:
            _ring.clear()
            if not _enabled:
                _ring = None
        del _dump_history[:]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def record_step(step, loss=None, lr=None, grad_norm=None, shapes=None,
                **extra):
    """Append one step record to the ring and feed the watchdog. No-op
    while diagnostics is disabled (single bool check)."""
    ring = _ring if _enabled else None
    if ring is None:
        return
    rec = {"ts": time.time(), "kind": "step", "step": step}
    if loss is not None:
        rec["loss"] = loss
    if lr is not None:
        rec["lr"] = lr
    if grad_norm is not None:
        rec["grad_norm"] = grad_norm
    if shapes is not None:
        rec["shapes"] = [list(s) for s in shapes]
    if _current_scope[0]:
        rec["scope"] = _current_scope[0]
    # compact telemetry digest: bare counter reads, no registry lock — the
    # full snapshot() goes into the post-mortem, not every ring entry
    rec["telemetry"] = {
        "compile_total": _M_COMPILE_TOTAL.value,
        "recompile_total": _M_RECOMPILE_TOTAL.value,
    }
    _gp = sys.modules.get(__package__ + ".goodput")
    if _gp is not None and _gp._enabled and _gp._t_enable is not None:
        # bare dict reads, no accountant lock — same compact-digest
        # discipline as the telemetry counters above
        _el = time.perf_counter() - _gp._t_enable
        if _el > 0:
            _good = sum((_gp._totals or {}).get(c, 0.0) for c in _gp.GOOD)
            rec["goodput_fraction"] = round(min(1.0, _good / _el), 4)
    rec.update(extra)
    with _lock:
        # appends share the readers' lock: records() list()s the deque and
        # a concurrent lockless append would raise "deque mutated during
        # iteration" inside the watchdog's dump, killing its thread
        ring.append(rec)
    _maybe_sample_memory()
    notify_progress(step)


def record_event(kind, **payload):
    """Append a non-step record (compile/recompile/custom) to the ring."""
    ring = _ring if _enabled else None
    if ring is None:
        return
    ev = {"ts": time.time(), "kind": kind}
    ev.update(payload)
    with _lock:
        ring.append(ev)


def annotate_step(step, **fields):
    """Merge fields into the most recent ring record for `step`. Lets a
    second observer of the same step (e.g. the estimator handler adding
    the loss to the Trainer's record) enrich it instead of appending a
    near-duplicate that halves effective ring coverage. Returns False —
    caller should record_step instead — when no such record exists."""
    ring = _ring if _enabled else None
    if ring is None:
        return False
    with _lock:
        for rec in reversed(ring):
            if rec.get("kind") == "step" and rec.get("step") == step:
                rec.update(fields)
                return True
    return False


def records(kind=None):
    """Recorded ring entries, oldest first ([] while never enabled)."""
    with _lock:
        evs = list(_ring) if _ring is not None else []
    return [e for e in evs if kind is None or e.get("kind") == kind]


def ring_tail(n=8):
    """The newest `n` flight-ring records, oldest first ([] while the
    recorder is off) — the bounded slice mx.scope's /statusz serves.
    Records are COPIED under the lock (and only the requested tail, not
    the whole ring): annotate_step() mutates the newest live record,
    and handing a reference to an HTTP thread's json.dumps would race
    that update (torn record, or RuntimeError mid-iteration)."""
    n = int(n)
    if n <= 0:
        return []
    out = []
    with _lock:
        if _ring is not None:
            for rec in reversed(_ring):
                out.append(dict(rec))
                if len(out) >= n:
                    break
    out.reverse()
    return out


# ---------------------------------------------------------------------------
# scope tracking (what the watchdog names when a step never completes)
# ---------------------------------------------------------------------------

def _scope_begin(name, step=None):
    global _current_scope
    _current_scope = (name, time.monotonic(), step)


def _scope_end():
    global _current_scope
    _current_scope = ("", 0.0, None)


class scope:
    """Context manager marking a region the watchdog can name: a hang
    inside it reports "stuck in <name> @ step <step>"."""

    def __init__(self, name, step=None):
        self.name = name
        self.step = step

    def __enter__(self):
        if _enabled:
            _scope_begin(self.name, self.step)
        return self

    def __exit__(self, *exc):
        if _enabled:
            _scope_end()
        return False


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Fires when no progress notification arrives within `deadline_s`.

    `clock` and `interval` are injectable for deterministic tests: the
    poll thread sleeps `interval` real seconds but all deadline math uses
    `clock()`. `_check()` is the synchronous decision step (tests call it
    directly). One fire per stall: after firing, the watchdog stays quiet
    until the next notify() re-arms it."""

    def __init__(self, deadline_s, on_fire=None, clock=time.monotonic,
                 interval=None, armed=True):
        self.deadline = float(deadline_s)
        self.clock = clock
        self.interval = interval if interval is not None else \
            min(max(self.deadline / 4.0, 0.05), 1.0)
        self.on_fire = on_fire
        self.fired = 0
        self.last_message = None
        self._last = clock()
        self._last_step = None
        # armed=False starts the watchdog DORMANT: the first notify() arms
        # it, so a minutes-long startup (first compile, data prep) before
        # any step completes can never read as a stall (mx.guard's
        # collective deadline starts this way)
        self._armed = armed
        self._suspended = 0
        self._stop = threading.Event()
        self._thread = None

    def notify(self, step=None, arm=True):
        """Progress: restart the idle clock. `arm=False` defers an armed
        deadline without waking a DORMANT one — mx.guard's pre-step beats
        (restore, input staging) are progress but must not arm the
        collective deadline before the first step completes."""
        self._last = self.clock()
        if step is not None:
            self._last_step = step
        if arm:
            self._armed = True

    def suspend(self):
        """Enter a legitimate long non-step region (checkpoint write,
        reshard restore, cold compile): the deadline cannot fire until
        the matching resume(). Nestable (counted)."""
        self._suspended += 1

    def resume(self):
        """Leave a suspended region; the suspended time does not count
        against the deadline (the idle clock restarts at resume)."""
        self._suspended = max(0, self._suspended - 1)
        if self._suspended == 0:
            self._last = self.clock()

    def _check(self):
        """One poll: returns True iff the deadline fired this call."""
        if self._suspended:
            return False
        idle = self.clock() - self._last
        if idle <= self.deadline or not self._armed:
            return False
        self._armed = False
        self.fired += 1
        name = _current_scope[0]
        where = f"stuck in {name}" if name else "no active scope"
        step = _current_scope[2] if _current_scope[2] is not None \
            else self._last_step
        msg = (f"mx.diagnostics watchdog: no step completed in {idle:.1f}s "
               f"(deadline {self.deadline:.1f}s) — {where} @ step {step}")
        self.last_message = msg
        print(msg, file=sys.stderr)
        if self.on_fire is not None:
            self.on_fire(msg)
        else:
            _dump_thread_stacks()
            try:
                dump(reason="watchdog", note=msg)
            except Exception:
                pass  # a hung run with an unwritable dir still gets stderr
        return True

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="mx-diagnostics-watchdog", daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self._check()
            except Exception as e:
                # the watchdog must outlive any single bad poll — a dead
                # thread means hang detection silently gone for the run
                print(f"mx.diagnostics watchdog: check failed: {e}",
                      file=sys.stderr)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def arm_watchdog(deadline_s=None, **kwargs):
    """Start (or restart) the module watchdog. deadline_s defaults to the
    `watchdog_deadline_s` knob; 0 means no watchdog (returns None)."""
    global _watchdog
    if deadline_s is None:
        deadline_s = config.get("watchdog_deadline_s")
    disarm_watchdog()
    if not deadline_s or float(deadline_s) <= 0:
        return None
    with _lock:
        _watchdog = Watchdog(deadline_s, **kwargs).start()
    return _watchdog


def disarm_watchdog():
    global _watchdog
    with _lock:
        w, _watchdog = _watchdog, None
    if w is not None:
        w.stop()


def notify_progress(step=None):
    w = _watchdog
    if w is not None:
        w.notify(step)


class suspend_watchdog:
    """Context manager for a NAMED legitimate long non-step region — a
    multi-GB checkpoint write, a resharding restore — during which
    neither the module watchdog nor the mx.guard collective deadline may
    fire (a long save is progress, not a hang). Both deadlines restart
    their idle clocks at exit, so a save just under the deadline can't
    trip it one poll later. Doubles as a diagnostics scope: a REAL hang
    *inside* the region still gets named by the post-mortem ("stuck in
    checkpoint.save @ step N") even though the timers stay quiet. Cheap
    enough for the disabled fast path: two module-global reads when
    nothing is armed."""

    def __init__(self, name, step=None):
        self.name = name
        self.step = step
        self._dogs = ()
        self._scoped = False

    def __enter__(self):
        dogs = []
        w = _watchdog
        if w is not None:
            dogs.append(w)
        g = sys.modules.get(__package__ + ".guard")
        if g is not None:
            d = g._deadline
            if d is not None:
                dogs.append(d)
        self._dogs = tuple(dogs)
        for d in self._dogs:
            d.suspend()
        if _enabled:
            self._scoped = True
            _scope_begin(self.name, self.step)
        return self

    def __exit__(self, *exc):
        if self._scoped:
            _scope_end()
        for d in self._dogs:
            d.resume()
        return False


def _dump_thread_stacks():
    """All-thread stacks to <rank dir>/watchdog_stacks.txt (the hang
    evidence faulthandler can produce without any signal plumbing)."""
    try:
        d = _rank_dir()
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "watchdog_stacks.txt"), "a") as f:
            f.write(f"=== watchdog fire at {time.time():.3f} ===\n")
            faulthandler.dump_traceback(file=f, all_threads=True)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# NaN/Inf sentinel
# ---------------------------------------------------------------------------

def _scalar(value):
    """Best-effort host float of an NDArray / jax array / python number
    (mean over non-scalar inputs)."""
    import numpy as np
    v = getattr(value, "_data", value)
    arr = np.asarray(v, dtype=np.float64)
    return float(arr) if arr.ndim == 0 else float(np.mean(arr))


def sentinel_check(value, what="loss", step=None):
    """Return `value` as a host float; on NaN/Inf write a post-mortem and
    raise NonFiniteError. The host fetch is the cost of the check — which
    is why the sentinel is opt-in (`nan_sentinel`)."""
    if value is None:
        return None
    v = _scalar(value)
    if math.isfinite(v):
        return v
    note = f"non-finite {what} at step {step}: {v}"
    try:
        dump(reason="nan", note=note)
    except OSError:
        pass
    raise NonFiniteError(
        f"{note} — post-mortem at {postmortem_path()!r}; rerun with "
        "mxnet_tpu.debug() for op-level NaN location")


def grad_global_norm(params):
    """Global L2 norm over the parameters' gradients (f32 accumulate).
    Device math + one host fetch; None when no gradients exist."""
    import jax.numpy as jnp
    total = None
    for p in params:
        try:
            g = p.grad() if callable(getattr(p, "grad", None)) else p
        except RuntimeError:
            continue  # grad_req='null' or uninitialized: nothing to check
        if g is None:
            continue
        d = getattr(g, "_data", g)
        s = jnp.sum(jnp.square(jnp.asarray(d).astype(jnp.float32)))
        total = s if total is None else total + s
    return float(jnp.sqrt(total)) if total is not None else None


# ---------------------------------------------------------------------------
# device-memory watermarks
# ---------------------------------------------------------------------------

_M_DEV_IN_USE = _telemetry.gauge(
    "device_bytes_in_use", "per-device HBM bytes currently allocated "
    "(jax memory_stats; absent on backends that don't report)")
_M_DEV_PEAK = _telemetry.gauge(
    "device_peak_bytes_in_use", "per-device peak HBM bytes — the OOM "
    "headroom watermark")
_M_HOST_RSS = _telemetry.gauge(
    "host_peak_rss_mb", "peak resident set size of this process (MiB)")


def _jax_devices_if_initialized():
    """jax.local_devices() ONLY when a backend already exists — a cold
    backend init inside an excepthook/watchdog could hang on a tunnel
    platform, so a run that never touched jax gets no device poll."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    try:
        from jax._src import xla_bridge
        if not xla_bridge._backends:
            return []
    except Exception:
        pass  # private API moved: fall through and poll anyway
    try:
        return jax.local_devices()
    except Exception:
        return []


def memory_watermarks():
    """Per-device memory stats via `device.memory_stats()` plus the host
    peak-RSS fallback (always present, so CPU-only runs still get a
    memory trajectory). Also publishes the telemetry gauges when
    telemetry is enabled; never initializes a jax backend (see
    _jax_devices_if_initialized)."""
    out = []
    for d in _jax_devices_if_initialized():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue  # CPU backend: no allocator stats — host RSS below
        rec = {"device": str(d)}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                  "largest_alloc_size"):
            if k in stats:
                rec[k] = stats[k]
        out.append(rec)
        _M_DEV_IN_USE.labels(device=str(d)).set(
            stats.get("bytes_in_use", 0))
        _M_DEV_PEAK.labels(device=str(d)).set(
            stats.get("peak_bytes_in_use", 0))
    try:
        rss_mb = host_peak_rss_mb()
        out.append({"device": "host", "peak_rss_mb": round(rss_mb, 1)})
        _M_HOST_RSS.set(rss_mb)
    except Exception:
        pass
    return out


def host_peak_rss_mb():
    """Peak resident set size of this process in MiB (the single home of
    the platform-sensitive ru_maxrss units; bench.py reads it too)."""
    import resource
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if sys.platform == "darwin":
        peak //= 1024  # ru_maxrss is bytes on macOS, KiB on Linux
    return peak / 1024.0


def _maybe_sample_memory():
    global _last_mem_sample
    now = time.monotonic()
    if now - _last_mem_sample < _MEM_SAMPLE_INTERVAL:
        return
    _last_mem_sample = now
    memory_watermarks()


# ---------------------------------------------------------------------------
# crash post-mortem
# ---------------------------------------------------------------------------

def _rank():
    if _rank_override is not None:
        return _rank_override
    for var in ("JAX_PROCESS_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _base_dir():
    return _dir_override or config.get("diagnostics_dir")


def _rank_dir():
    return os.path.join(_base_dir(), str(_rank()))


def postmortem_path():
    """Where this process's post-mortem dump lands."""
    return os.path.join(_rank_dir(), "postmortem.json")


def _profiler_tail(n=100):
    from . import profiler
    with profiler._lock:
        return list(profiler._events)[-n:]


def dump(reason="manual", exc_info=None, note=None, path=None):
    """Write the post-mortem JSON: ring buffer, telemetry registry
    snapshot, config snapshot, memory watermarks, chrome-trace tail, and
    (when crashing) the exception + traceback. Returns the path. Last
    dump wins the file; earlier dumps this process (e.g. a recovered
    watchdog fire hours before a clean exit) survive as `prior_dumps`."""
    pm = {
        "schema": 1,
        "rank": _rank(),
        "pid": os.getpid(),
        "ts": time.time(),
        "reason": reason,
        "argv": list(sys.argv),
    }
    if note:
        pm["note"] = note
    with _lock:
        if _dump_history:
            pm["prior_dumps"] = [{"reason": r, "ts": t}
                                 for r, t in _dump_history]
    if exc_info is not None:
        etype, evalue, etb = exc_info
        pm["exception"] = {
            "type": getattr(etype, "__name__", str(etype)),
            "message": str(evalue),
            "traceback": traceback.format_exception(etype, evalue, etb),
        }
    w = _watchdog
    if w is not None:
        pm["watchdog"] = {
            "deadline_s": w.deadline,
            "fired": w.fired,
            "last_step": w._last_step,
            "seconds_since_progress": round(w.clock() - w._last, 3),
        }
    if _current_scope[0]:
        pm["scope"] = {"name": _current_scope[0],
                       "entered_s_ago": round(
                           time.monotonic() - _current_scope[1], 3),
                       "step": _current_scope[2]}
    pm["ring"] = records()
    try:
        pm["telemetry"] = _telemetry.snapshot()
    except Exception as e:
        pm["telemetry"] = {"error": str(e)}
    try:
        pm["config"] = config.describe()
    except Exception as e:
        pm["config"] = {"error": str(e)}
    try:
        pm["memory"] = memory_watermarks()
    except Exception as e:
        pm["memory"] = [{"error": str(e)}]
    try:
        # cost attribution (mx.inspect — imported lazily: inspect imports
        # this module): an OOM post-mortem then names the executable with
        # the largest peak_bytes right next to the memory watermarks
        from . import inspect as _inspect_mod
        if _inspect_mod._registry:
            pm["inspect"] = _inspect_mod.snapshot()
    except Exception as e:
        pm["inspect"] = {"error": str(e)}
    try:
        # resume provenance (mx.resilience — checked via sys.modules so a
        # run that never touched resilience pays no import): names the
        # checkpoint this process restored from, so a post-mortem of a
        # relaunched run shows where it picked up
        _res = sys.modules.get(__package__ + ".resilience")
        if _res is not None:
            if _res._resume_info:
                pm["resume"] = dict(_res._resume_info)
            if _res.restart_count():
                pm.setdefault("resume", {})["restart_count"] = \
                    _res.restart_count()
    except Exception as e:
        pm["resume"] = {"error": str(e)}
    try:
        # memory-safety story (mx.memsafe — via sys.modules so a run that
        # never touched it pays no import): the last pre-flight budget
        # check, every degradation-ladder transition, and the OOM count —
        # an OOM post-mortem then shows what was predicted and what the
        # ladder already traded away
        _ms = sys.modules.get(__package__ + ".memsafe")
        if _ms is not None and (_ms._transitions or _ms._last_check
                                or _ms._oom_events):
            pm["memsafe"] = _ms.snapshot()
    except Exception as e:
        pm["memsafe"] = {"error": str(e)}
    try:
        # gang-timeline story (mx.trace — via sys.modules so a run that
        # never touched it pays no import): sampling config, span/skew
        # volume, the LAST measured step-skew probe (spread + straggler
        # rank), and where this rank's trace.jsonl landed — a post-mortem
        # of a stalled gang then names the straggler next to the hang
        # evidence, and tools/trace_report.py knows what to merge
        _tr = sys.modules.get(__package__ + ".trace")
        if _tr is not None and (_tr._enabled or _tr._skews):
            pm["trace"] = _tr.snapshot()
    except Exception as e:
        pm["trace"] = {"error": str(e)}
    try:
        # liveness/SDC story (mx.guard — via sys.modules so a run that
        # never touched it pays no import): last heartbeat, deadline and
        # digest-vote config, the last SDC verdict, and — when the
        # collective deadline fired — the suspected dead peer, so
        # tools/postmortem_report.py can name the rank that stopped
        # heartbeating next to the hang evidence
        _g = sys.modules.get(__package__ + ".guard")
        if _g is not None and (_g._enabled or _g._peer_lost_info
                               or _g._last_sdc):
            pm["guard"] = _g.snapshot()
    except Exception as e:
        pm["guard"] = {"error": str(e)}
    try:
        # wall-clock accounting story (mx.goodput — via sys.modules so a
        # run that never touched it pays no import): per-category
        # goodput/badput seconds, the fraction, top badput cause, and
        # the progress high-water mark — a post-mortem of a thrashing
        # run then shows where its wall-clock went
        _gp = sys.modules.get(__package__ + ".goodput")
        if _gp is not None and _gp._enabled:
            pm["goodput"] = _gp.snapshot()
    except Exception as e:
        pm["goodput"] = {"error": str(e)}
    try:
        pm["profiler_tail"] = _profiler_tail()
    except Exception:
        pm["profiler_tail"] = []
    path = path or postmortem_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(pm, f, default=str)
    os.replace(tmp, path)  # crash-during-dump leaves the previous dump intact
    with _lock:
        _dump_history.append((reason, pm["ts"]))
    return path


def _excepthook(etype, evalue, etb):
    try:
        dump(reason="exception", exc_info=(etype, evalue, etb))
    except Exception as e:
        print(f"mx.diagnostics: post-mortem dump failed: {e}",
              file=sys.stderr)
    hook = _prev_excepthook or sys.__excepthook__
    hook(etype, evalue, etb)


def _atexit_dump():
    # a crash already wrote its dump through the excepthook — that IS the
    # exit state. Anything else (no dump yet, or a RECOVERED watchdog/nan
    # fire hours earlier) gets a final reason='exit' dump so a rank that
    # stalled once but finished clean isn't reported as HUNG forever; the
    # earlier fire survives in prior_dumps.
    if not (_installed and _enabled):
        return
    if _dump_history and _dump_history[-1][0] == "exception":
        return
    try:
        dump(reason="exit")
    except Exception:
        pass  # nothing useful to do with a write error during interpreter exit


def install(diagnostics_dir=None, rank=None, ring_size=None):
    """Arm the whole post-mortem layer: enable the flight recorder, chain
    `sys.excepthook`, register the atexit writer, point `faulthandler` at
    `<rank dir>/faulthandler.log` (hard-crash stacks: SIGSEGV/SIGABRT),
    and start the watchdog when `watchdog_deadline_s` > 0. Idempotent;
    returns the per-rank directory."""
    global _installed, _prev_excepthook, _atexit_registered
    global _dir_override, _rank_override, _faulthandler_file
    with _lock:
        if diagnostics_dir is not None:
            _dir_override = str(diagnostics_dir)
        if rank is not None:
            _rank_override = int(rank)
    enable(ring_size=ring_size)
    d = _rank_dir()
    try:
        os.makedirs(d, exist_ok=True)
        if _faulthandler_file is None:
            _faulthandler_file = open(
                os.path.join(d, "faulthandler.log"), "a")
            faulthandler.enable(file=_faulthandler_file, all_threads=True)
    except OSError as e:
        print(f"mx.diagnostics: cannot write {d!r}: {e} — post-mortems "
              "will retry at dump time", file=sys.stderr)
    with _lock:
        if not _installed:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _excepthook
            _installed = True
        if not _atexit_registered:
            atexit.register(_atexit_dump)
            _atexit_registered = True
    if config.get("watchdog_deadline_s") > 0 and _watchdog is None:
        arm_watchdog()
    return d


def uninstall():
    """Undo install() (tests): restore the excepthook, stop the watchdog,
    release faulthandler. The atexit hook stays registered but checks
    `_installed` and becomes a no-op."""
    global _installed, _prev_excepthook, _faulthandler_file
    global _dir_override, _rank_override
    disarm_watchdog()
    with _lock:
        if _installed:
            sys.excepthook = _prev_excepthook or sys.__excepthook__
            _prev_excepthook = None
            _installed = False
        if _faulthandler_file is not None:
            try:
                faulthandler.disable()
                _faulthandler_file.close()
            except OSError:
                pass
            _faulthandler_file = None
        _dir_override = None
        _rank_override = None
    disable()


if config.get("diagnostics"):
    install()
