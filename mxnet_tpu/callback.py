"""Training callbacks (reference: `python/mxnet/callback.py`)."""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric", "ProgressBar",
           "module_checkpoint", "LogValidationMetricsCallback"]


class Speedometer:
    """Log samples/sec every `frequent` batches (reference: Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s" % (
                        param.epoch, count, speed,
                        "\t".join(f"{n}={v:.6f}" for n, v in name_value))
                else:
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" % (
                        param.epoch, count, speed)
                logging.info(msg)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference: mx.callback.do_checkpoint)."""

    def _callback(iter_no, sym=None, arg=None, aux=None, module=None):
        if (iter_no + 1) % period == 0 and module is not None:
            module.save_checkpoint(prefix, iter_no + 1)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


module_checkpoint = do_checkpoint


class ProgressBar:
    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        count = param.nbatch
        filled = int(round(self.length * count / float(self.total)))
        bar = "=" * filled + "-" * (self.length - filled)
        print(f"[{bar}] {count}/{self.total}", end="\r")


class LogValidationMetricsCallback:
    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
