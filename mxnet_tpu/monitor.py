"""Training monitor (reference: `python/mxnet/monitor.py` `Monitor` —
periodic statistics over layer outputs, parameters, and gradients, regex
filtered, printed per batch).

Gluon integration uses Block forward hooks (outputs recorded per child
block); parameter/gradient stats come straight from `collect_params()`.
The classic Module path gets the same via `Module.install_monitor`."""
from __future__ import annotations

import re
import weakref

from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x):
    return x.abs().mean()


class Monitor:
    """Collect activation/param/grad statistics every `interval` batches.

    Usage (matching the reference):
        mon = Monitor(interval=10, pattern='.*fc.*')
        mon.install(net)              # gluon Block (recursive)
        for batch in data:
            mon.tic()
            ... forward/backward/step ...
            mon.toc_print()           # or rows = mon.toc()
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_gradient=True):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.monitor_gradient = monitor_gradient
        self.step = 0
        self.activated = False
        self._activations = []
        self._params = None
        # block -> set of names it is hooked under; weak so a dead block's
        # entry (and its reused id) can never shadow a new block
        self._installed = weakref.WeakKeyDictionary()

    # -- wiring ----------------------------------------------------------
    def install(self, block, prefix=""):
        """Recursively hook a gluon Block; records each child's output when
        the monitor is activated. Also registers the block's parameters for
        param/grad statistics. Idempotent per (block, name) — a repeated
        install would duplicate every forward hook and double-count
        activations — while a shared block instance reachable under two
        prefixes still reports under both names, and the recursion always
        walks the children, so children added after a first install get
        hooked by a re-install."""
        name = prefix or type(block).__name__.lower()
        hooked_names = self._installed.setdefault(block, set())
        if name not in hooked_names:
            hooked_names.add(name)

            def hook(blk, inputs, output, _name=name):
                if not self.activated:
                    return
                outs = output if isinstance(output, (list, tuple)) \
                    else [output]
                for i, o in enumerate(outs):
                    if isinstance(o, NDArray):
                        tag = _name if len(outs) == 1 \
                            else f"{_name}_output{i}"
                        self._activations.append((tag, o))

            block.register_forward_hook(hook)
        for cname, child in getattr(block, "_children", {}).items():
            self.install(child, f"{name}.{cname}")
        if prefix == "":
            self._params = block.collect_params()
        return self

    # -- per-batch protocol ---------------------------------------------
    def tic(self):
        """Start a batch; activates collection every `interval` calls."""
        self._activations = []
        self.activated = (self.step % self.interval) == 0
        self.step += 1
        return self.activated

    def toc(self):
        """End the batch: returns [(step, name, stat_value_str)] for every
        recorded activation, parameter, and gradient matching the
        pattern."""
        if not self.activated:
            return []
        rows = []
        for name, arr in self._activations:
            if self.re_pattern.match(name):
                rows.append((self.step - 1, name, self._fmt(arr)))
        if self._params is not None:
            for pname, param in self._params.items():
                if not self.re_pattern.match(pname):
                    continue
                try:
                    rows.append((self.step - 1, pname,
                                 self._fmt(param.data())))
                except Exception:
                    continue  # uninitialized
                if self.monitor_gradient:
                    g = param.grad() if param.grad_req != "null" else None
                    if g is not None:
                        rows.append((self.step - 1, pname + "_grad",
                                     self._fmt(g)))
        self.activated = False
        self._activations = []
        if self.sort:
            rows.sort(key=lambda r: r[1])
        return rows

    def toc_print(self):
        rows = self.toc()
        for step, name, stat in rows:
            print(f"Batch: {step:7d} {name:40s} {stat}")
        return rows

    def _fmt(self, arr):
        out = self.stat_func(arr)
        if isinstance(out, NDArray):
            out = float(out.asnumpy().reshape(-1)[0]) \
                if out.size == 1 else out.asnumpy()
        return str(out)
