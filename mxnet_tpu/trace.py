"""mx.trace — cross-rank distributed step tracing with straggler and
critical-path attribution.

The observability stack so far explains ONE process: `mx.inspect`'s
MFU/roofline and telemetry's input-stall attribution are static estimates
or single-rank aggregates, so "why is the GANG slow" — a straggler rank,
collective arrival skew, a host input stall on one worker — was answered
by eyeballing per-rank JSONL files. Data-parallel collectives serialize on
the slowest arriver (PAPERS.md arxiv 2004.13336: weight-update collectives
dominate as replicas scale), which makes the gang-wide timeline the unit
of diagnosis, not the rank. This module is that measured timeline layer:

  * **sampling span recorder** — host-side spans tagged `(rank, step)` at
    the hook sites that already exist: dataflow batch-wait and H2D
    staging, ShardedTrainer dispatch and fence, block/step compile,
    resilience checkpoint save. Every `trace_sample_every`-th step is
    recorded (compiles/checkpoints always — rare and seconds-scale);
    sampled steps are additionally wrapped in
    `jax.profiler.TraceAnnotation` so XLA device traces carry the same
    step id as the host spans.
  * **skew probe** — every `trace_skew_every` sampled steps, each rank
    wall-stamps its arrival at the collective boundary (a tiny
    timestamped all-gather when jax runs multi-process), measuring
    per-rank clock offset and step-arrival spread. Feeds the
    `step_skew_seconds` / `straggler_rank` telemetry gauges, a
    flight-ring "trace" entry, and the post-mortem "trace" section.
  * **per-rank span files** — with `trace_dir` set, spans append to
    `<dir>/<rank>/trace.jsonl` behind a meta line carrying this rank's
    wall-clock epoch (and the gang epoch tools/launch.py --trace-dir
    exports), so `tools/trace_report.py` can merge all ranks into one
    clock-aligned Perfetto/chrome trace (one track per rank) and print a
    measured gang-wide verdict: input-bound / compute-bound /
    comm-skew-bound, naming the straggler rank and its dominant span.

Clock model: spans timestamp against the process-wide monotonic epoch in
`mxnet_tpu.util` — the SAME epoch mx.profiler's chrome events and
telemetry's event mirror use — and the meta line maps that epoch to wall
time, so merged multi-rank timelines align without per-file clock math.

Cost model: DISABLED (the default) is the production fast path — every
hook site checks one module-level bool and falls through; no span buffer
exists, no locks are taken, nothing allocates (`ci/run.sh sanity` asserts
the hook sites make zero recorder calls). Enable with
`mx.trace.enable()` / `MXNET_TPU_TRACE=on` / `tools/launch.py
--trace-dir`.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import time

from . import _locklint
from . import config as _config
from . import telemetry as _telemetry
from . import util as _util

__all__ = [
    "enable", "disable", "enabled", "reset",
    "sampled", "record_span", "annotate", "skew_tick",
    "flush", "trace_path", "spans", "skews", "snapshot",
    "skew_p99_ms", "skew_verdict", "critical_path",
]

_lock = _locklint.make_lock("trace.recorder")
_enabled = False          # the fast-path bool; hook sites read it directly
_dir = ""                 # per-rank files under <_dir>/<rank>/trace.jsonl
_rank_override = None
_sample_every = 1
_skew_every = 16
_buf = None               # pending records; None while disabled (zero-alloc)
_meta_paths = set()       # targets that already carry their meta line
_ticks = {}               # per-name counters: sampling for step-less spans
_agg = {}                 # (cat, name) -> [count, total_us] (critical path)
_skews = []               # skew probe records (bounded, drop-oldest)
_recorded = 0
_dropped = 0
_skew_failed = False      # a failed collective probe disables further ones
_flush_warned = False
_next_flush_try = 0.0     # monotonic backoff after a failed flush
_FLUSH_EVERY = 256        # buffered records per file append
_FLUSH_RETRY_S = 5.0      # wait after a failed flush before retrying
_MAX_BUF = 100_000        # in-memory record bound (with or without a dir)
_MAX_SKEWS = 4096

# gang-wide skew surfaced as ordinary telemetry series (no-ops while
# telemetry is disabled, like every other gauge in the registry)
_M_SKEW = _telemetry.gauge(
    "step_skew_seconds", "step-arrival spread across ranks at the "
    "collective boundary, from the last mx.trace skew probe (collectives "
    "serialize on the slowest arriver — this is the measured cost)")
_M_STRAGGLER = _telemetry.gauge(
    "straggler_rank", "rank that arrived LAST at the collective boundary "
    "in the last mx.trace skew probe — the gang's current straggler")


def enabled():
    """True when the span recorder is on (hot paths read the module
    global `_enabled` directly — this accessor is the public spelling)."""
    return _enabled


def enable(trace_dir=None, rank=None, sample_every=None, skew_every=None):
    """Arm the recorder. Arguments override the `trace_dir` /
    `trace_sample_every` / `trace_skew_every` knobs (read once here — the
    per-span hot path never touches the config registry)."""
    global _enabled, _dir, _rank_override, _sample_every, _skew_every, _buf
    with _lock:
        if trace_dir is not None:
            _dir = str(trace_dir)
        elif not _dir:
            _dir = _config.get("trace_dir")
        if rank is not None:
            _rank_override = int(rank)
        _sample_every = max(1, int(
            sample_every if sample_every is not None
            else _config.get("trace_sample_every")))
        _skew_every = int(skew_every if skew_every is not None
                          else _config.get("trace_skew_every"))
        if _buf is None:
            _buf = []
        _enabled = True


def disable():
    global _enabled
    _enabled = False


def reset():
    """Drop recorded state (tests and run boundaries). While disabled the
    buffer itself is released, restoring the zero-allocation fast path."""
    global _buf, _recorded, _dropped
    global _skew_failed, _dir, _rank_override, _next_flush_try
    with _lock:
        _next_flush_try = 0.0
        _buf = [] if _enabled else None
        _ticks.clear()
        _agg.clear()
        del _skews[:]
        _meta_paths.clear()
        _recorded = 0
        _dropped = 0
        _skew_failed = False
        if not _enabled:
            _dir = ""
            _rank_override = None


def _rank():
    if _rank_override is not None:
        return _rank_override
    for var in ("JAX_PROCESS_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _generation():
    """Which relaunch generation this process belongs to (the
    supervised-relaunch counter tools/launch.py exports; 0 standalone).
    Stamped into skew records so the offline cross-rank match pairs
    arrival stamps WITHIN a generation — a resumed gang replays step
    ids, and matching a survivor's replayed stamp against a dead rank's
    pre-restart stamp would read the restart backoff as arrival skew."""
    try:
        return int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    except ValueError:
        return 0


def _gang_epoch_ns():
    """The shared gang trace epoch tools/launch.py --trace-dir exports
    (one wall timestamp for the whole gang), or None standalone."""
    v = os.environ.get("MXNET_TPU_TRACE_EPOCH_NS")
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def trace_path():
    """Where this rank's span file lands (None when trace_dir is unset)."""
    if not _dir:
        return None
    return os.path.join(_dir, str(_rank()), "trace.jsonl")


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------

def _trim_locked():
    """Drop-oldest bound on the record buffer (caller holds _lock),
    applied with OR without a trace_dir — an unwritable dir (every flush
    failing and re-queuing) must degrade to the same bounded in-memory
    buffer, not grow RSS. Trims in batches so eviction is amortized O(1)
    per span instead of an O(len) list shift per record once full."""
    global _dropped
    if len(_buf) > _MAX_BUF:
        cut = len(_buf) - _MAX_BUF + max(1, _MAX_BUF // 10)
        cut = min(cut, len(_buf))
        del _buf[:cut]
        _dropped += cut


def _flush_due_locked():
    """Whether the recorder should attempt a periodic flush (caller
    holds _lock). A failed flush backs off _FLUSH_RETRY_S so a full or
    read-only disk costs one open() per retry window, not one O(buffer)
    copy-and-fail per span."""
    return (bool(_dir) and len(_buf) >= _FLUSH_EVERY
            and time.monotonic() >= _next_flush_try)


def sampled(step):
    """True when `step` is one of the sampled steps (the trainer uses
    this to decide up front whether to stamp/fence/annotate a step)."""
    return step % _sample_every == 0


def record_span(name, t0, t1=None, step=None, cat="host", always=False,
                **extra):
    """Record one host-side span: `t0`/`t1` are raw time.perf_counter()
    readings (seconds; `t1` defaults to now), mapped onto the shared
    monotonic epoch. Sampling: `always` records unconditionally
    (compiles, checkpoints); a `step` records iff the step is sampled;
    step-less spans (input streams) sample on a per-name counter with
    the same stride. Returns True iff the span was recorded. Callers
    gate on the module bool — this function is never reached while
    disabled (ci sanity counts the calls)."""
    global _recorded, _dropped
    if not _enabled:
        return False
    if t1 is None:
        t1 = time.perf_counter()
    with _lock:
        if _buf is None:
            return False    # disabled+reset raced a recording thread
        if not always:
            if step is not None:
                if step % _sample_every:
                    return False
            else:
                n = _ticks.get(name, 0)
                _ticks[name] = n + 1
                if n % _sample_every:
                    return False
        ev = {"kind": "span", "name": name, "cat": cat,
              "ts_us": round(_util.perf_to_us(t0), 1),
              "dur_us": round((t1 - t0) * 1e6, 1), "rank": _rank()}
        if step is not None:
            ev["step"] = int(step)
        if extra:
            ev.update(extra)
        a = _agg.get((cat, name))
        if a is None:
            _agg[(cat, name)] = [1, ev["dur_us"]]
        else:
            a[0] += 1
            a[1] += ev["dur_us"]
        _buf.append(ev)
        _recorded += 1
        _trim_locked()
        due = _flush_due_locked()
    if due:
        _safe_flush()
    return True


def annotate(step):
    """Context manager wrapping one sampled step in a
    jax.profiler.TraceAnnotation carrying the same (rank, step) tag as
    the host spans, so XLA device traces and mx.trace timelines join on
    the step id. Only called for sampled steps while enabled."""
    import jax
    return jax.profiler.TraceAnnotation("mx.trace.step", step=int(step),
                                        rank=_rank())


# ---------------------------------------------------------------------------
# skew probe
# ---------------------------------------------------------------------------

def skew_tick(step):
    """Run the skew probe on every `trace_skew_every`-th SAMPLED step.
    The cadence is a pure function of the step id — NOT a local counter —
    because the multi-process probe is a blocking collective: every rank
    must reach it at the same global step, and a rank-local event (a
    jit-cache miss also calls this, and misses can be rank-local under
    bucketed shapes) must not desynchronize who probes when."""
    if not _enabled or _skew_every <= 0:
        return
    if step % _sample_every:
        return   # an always-traced (cache-miss) step that is not sampled
    if (step // _sample_every) % _skew_every:
        return
    _skew_probe(step)


def _skew_probe(step):
    """One probe: wall-stamp this rank's arrival; in a multi-process jax
    world all-gather the stamps so every rank sees the gang's spread and
    straggler live. Single-process worlds still record the local stamp —
    tools/trace_report.py cross-matches the per-rank records by step to
    measure the spread offline (the launch.py-without-jax.distributed
    case)."""
    global _skew_failed
    t_ns = time.time_ns()
    ts_us = _util.now_us()
    times = None
    try:
        jax = sys.modules.get("jax")
        # once a collective probe failed, never retry it this process:
        # a rank whose peers stopped answering must not block a sampled
        # step in an all-gather they will never join (stamps still
        # record — the offline step match needs no collective)
        if not _skew_failed and jax is not None \
                and jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            g = multihost_utils.process_allgather(
                np.asarray([t_ns], np.int64))
            times = [int(x) for x in np.asarray(g).ravel()]
    except Exception as e:  # pragma: no cover - backend-dependent
        if not _skew_failed:
            _skew_failed = True
            import warnings
            warnings.warn(f"mx.trace skew probe unavailable: {e}; "
                          "per-rank arrival stamps still record")
    if times is None:
        times = [t_ns]
    t_min = min(times)
    spread_s = (max(times) - t_min) / 1e9
    straggler = max(range(len(times)), key=lambda i: times[i]) \
        if len(times) > 1 else _rank()
    rec = {"kind": "skew", "ts_us": round(ts_us, 1), "step": int(step),
           "rank": _rank(), "t_wall_ns": t_ns, "gen": _generation(),
           "participants": len(times), "spread_s": round(spread_s, 6),
           "straggler_rank": straggler,
           "offsets_ns": [t - t_min for t in times]}
    global _dropped
    with _lock:
        _skews.append(dict(rec))
        if len(_skews) > _MAX_SKEWS:
            del _skews[0]
        due = False
        if _buf is not None:
            _buf.append(rec)
            _trim_locked()
            due = _flush_due_locked()
    _M_SKEW.set(spread_s)
    _M_STRAGGLER.set(straggler)
    _telemetry.event("trace_skew", step=int(step), spread_s=spread_s,
                     straggler_rank=straggler, participants=len(times))
    try:
        from . import diagnostics as _diagnostics
        _diagnostics.record_event("trace", step=int(step),
                                  spread_s=spread_s,
                                  straggler_rank=straggler)
    except Exception:
        pass
    if due:
        _safe_flush()


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def _meta_record():
    return {"kind": "meta", "schema": 1, "rank": _rank(),
            "pid": os.getpid(), "ts": time.time(),
            "epoch_unix_ns": _util.epoch_unix_ns(),
            "gang_epoch_ns": _gang_epoch_ns(),
            "sample_every": _sample_every, "skew_every": _skew_every}


def flush(path=None):
    """Append buffered records to `path` (default: this rank's
    trace_dir/<rank>/trace.jsonl) behind a one-time-PER-TARGET meta line
    (an explicit flush to a side path must not rob the rank file of the
    epoch anchor trace_report aligns on), and clear the buffer. Returns
    the path, or None when there is no target (the buffer then stays,
    bounded)."""
    path = path or trace_path()
    if path is None:
        return None
    global _next_flush_try
    with _lock:
        recs = list(_buf) if _buf else []
        if _buf:
            del _buf[:]
        need_meta = path not in _meta_paths
        _meta_paths.add(path)
    meta_ok = not need_meta
    written = 0
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # line-buffered: each write hands its line to the OS, so
        # `written` below reflects lines actually out the door — a
        # full-buffer deferral would otherwise surface the OSError at
        # close() with every record already counted (and then lost)
        with open(path, "a", buffering=1) as f:
            if need_meta:
                f.write(json.dumps(_meta_record()) + "\n")
                meta_ok = True
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
                written += 1
    except OSError:
        # a failed write must not lose the spans _safe_flush promises
        # stay buffered — but lines already handed to the OS before the
        # failure may be in the file, so only the UNWRITTEN suffix goes
        # back (front, order kept; a torn final line is skipped by
        # trace_report's loader, not duplicated), the meta line is only
        # re-armed when it never made it out, and retries back off
        with _lock:
            if not meta_ok:
                _meta_paths.discard(path)
            if _buf is not None:
                _buf[:0] = recs[written:]
                _trim_locked()
            _next_flush_try = time.monotonic() + _FLUSH_RETRY_S
        raise
    with _lock:
        _next_flush_try = 0.0
    return path


def _safe_flush():
    """Periodic flush that must not kill the training step it observes:
    an unwritable trace_dir warns once and keeps recording in memory."""
    global _flush_warned
    try:
        flush()
    except OSError as e:
        if not _flush_warned:
            _flush_warned = True
            import warnings
            warnings.warn(f"mx.trace flush to {trace_path()!r} failed: {e}; "
                          "spans stay buffered (warning once)")


def spans(tail=None):
    """Buffered (not yet flushed) span records, oldest first. `tail`
    bounds the work to the newest N spans — the scrape path (mx.scope
    /tracez) must not copy a 100k-record buffer under the same lock the
    step hot path's record_span takes, just to return 64 of them."""
    with _lock:
        if not _buf:
            return []
        if tail is None:
            return [dict(r) for r in _buf if r.get("kind") == "span"]
        out = []
        if tail > 0:
            for r in reversed(_buf):
                if r.get("kind") == "span":
                    out.append(dict(r))
                    if len(out) >= tail:
                        break
            out.reverse()
        return out


def skews():
    """Skew probe records this process, oldest first (kept in memory even
    after flushes, bounded)."""
    with _lock:
        return [dict(r) for r in _skews]


def snapshot():
    """Plain-data summary for the post-mortem "trace" section: sampling
    config, span/skew volume, this rank's file, and the last measured
    skew."""
    with _lock:
        return {
            "rank": _rank(),
            "sample_every": _sample_every,
            "skew_every": _skew_every,
            "spans_recorded": _recorded,
            "spans_buffered": len(_buf or ()),
            "spans_dropped": _dropped,
            "skew_probes": len(_skews),
            "last_skew": dict(_skews[-1]) if _skews else None,
            "path": trace_path(),
        }


def skew_p99_ms():
    """p99 of the measured multi-participant arrival spreads, in ms —
    None when no probe saw more than one participant (a single process
    cannot measure gang skew by itself; the merged report can)."""
    with _lock:
        spreads = sorted(s["spread_s"] for s in _skews
                         if s.get("participants", 1) > 1)
    if not spreads:
        return None
    idx = min(len(spreads) - 1, int(round(0.99 * (len(spreads) - 1))))
    return round(spreads[idx] * 1e3, 3)


def skew_verdict():
    """Live gang-skew summary for mx.scope's /statusz (the offline
    report in tools/trace_report.py stays the authoritative verdict —
    this is what a live scrape can know from THIS rank's probes): the
    last measured arrival spread, the suspected straggler rank, and the
    p99 across probes. None before any probe ran."""
    with _lock:
        last = dict(_skews[-1]) if _skews else None
        probes = len(_skews)
    if last is None:
        return None
    return {
        "probes": probes,
        "step": last.get("step"),
        "participants": last.get("participants", 1),
        "spread_ms": round(last.get("spread_s", 0.0) * 1e3, 3),
        "straggler_rank": last.get("straggler_rank"),
        "skew_p99_ms": skew_p99_ms(),
    }


def critical_path():
    """This rank's dominant STEADY-STATE span — the local leg of the
    gang critical path: {"span", "cat", "fraction", "total_s"} of the
    step/input span with the most recorded time, or None before any.
    Always-recorded compile/checkpoint spans are excluded: they are
    one-off seconds-scale events that would otherwise win every run
    (bench publishes this field — warmup compile time is not the
    critical path), the same exclusion tools/trace_report.py makes for
    its compute-bound dominant span."""
    with _lock:
        steady = {k: v for k, v in _agg.items()
                  if k[0] in ("step", "input", "serve")}
        if not steady:
            return None
        total = sum(t for _, t in steady.values())
        (cat, name), (count, t) = max(steady.items(),
                                      key=lambda kv: kv[1][1])
    if total <= 0:
        return None
    return {"span": name, "cat": cat, "fraction": round(t / total, 4),
            "total_s": round(t / 1e6, 6), "count": count}


@atexit.register
def _flush_at_exit():
    if _enabled and _dir:
        try:
            flush()
        except OSError:
            pass  # nothing useful to do with a write error at interpreter exit


if _config.get("trace") == "on":
    enable()
