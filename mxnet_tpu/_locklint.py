"""Instrumented-lock layer — the mx.check concurrency analysis (tsan-lite).

PR 5 shipped a real deadlock: tools/launch.py's signal handler called a
blocking `Popen.wait()` while the interrupted main thread held the same
`_waitpid_lock`. That class of bug — two execution contexts taking the
same locks in opposite orders — is invisible to tests that never hit the
race window, but it is STATICALLY visible in the acquisition-order graph:
if lock A is ever held while B is acquired, and elsewhere B is held while
A is acquired, the pair can deadlock. This module records that graph.

`make_lock(name)` / `make_rlock(name)` are drop-in factories the
instrumented modules (telemetry, diagnostics, dataflow, resilience,
inspect, memsafe, profiler, trace — and tools/launch.py) use instead of raw
`threading.Lock()` / `threading.RLock()` (the mx.check `raw-lock` AST
rule enforces it). Disarmed (the default) they return the PLAIN
threading primitive — zero wrapper, zero overhead, byte-for-byte the old
behavior. Armed (`MXNET_TPU_CHECK_THREADS=1`, the tsan-lite CI sweep)
they return a `CheckedLock` that:

  * records every held-while-acquiring edge into a process-global
    acquisition-order graph, with the acquiring stack captured per edge;
  * raises `LockOrderError` the moment an edge CLOSES A CYCLE, reporting
    BOTH acquisition stacks — the deadlock is diagnosed from one
    interleaving that did not hang, instead of reproduced from the one
    that did;
  * flags a blocking re-acquire of a non-reentrant lock on the same
    thread (`self-deadlock`: certain deadlock, the launch.py bug shape);
  * backs `GuardedDict`, whose mutations assert the guard lock is held
    (`unguarded-mutation`) — the shared-structure half of tsan-lite.

Stdlib-only ON PURPOSE: tools/launch.py stays jax-free and loads this
file directly (importlib by path, no package import), so the launch
supervisor's locks ride the same analysis as the framework's.
"""
from __future__ import annotations

import os
import threading
import traceback

__all__ = [
    "LockOrderError", "CheckedLock", "GuardedDict",
    "make_lock", "make_rlock", "guarded_dict",
    "armed", "arm", "disarm", "reset",
    "cycles", "unguarded_mutations", "lock_graph", "findings",
]


def _env_armed():
    return os.environ.get("MXNET_TPU_CHECK_THREADS", "").lower() in (
        "1", "true", "yes", "on")


_armed = _env_armed()      # snapshot at import; arm()/disarm() for tests
_graph_lock = threading.Lock()    # guards the order graph + finding lists
_edges = {}                # (a_name, b_name) -> edge record dict
_adj = {}                  # a_name -> set of b_name (a held while b taken)
_cycles = []               # finding dicts (kept even after the raise)
_mutations = []            # unguarded-mutation finding dicts
_held = threading.local()  # per-thread stack of held CheckedLocks


class LockOrderError(RuntimeError):
    """A lock acquisition closed a cycle in the acquisition-order graph:
    two contexts take the same locks in opposite orders, so the schedule
    that interleaves them deadlocks. Carries the finding dict (both
    acquisition stacks included) as `.finding`."""

    def __init__(self, message, finding=None):
        super().__init__(message)
        self.finding = finding or {}


def armed():
    return _armed


def arm():
    global _armed
    _armed = True


def disarm():
    global _armed
    _armed = False


def reset():
    """Drop the recorded graph and findings (test boundaries)."""
    with _graph_lock:
        _edges.clear()
        _adj.clear()
        del _cycles[:]
        del _mutations[:]


_THIS_FILE = os.path.abspath(__file__)


def _stack(skip=0):
    """Compact acquisition stack: 'file:line in func' lines, innermost
    last, with this module's own frames trimmed so the innermost line is
    the CALLER's acquire site."""
    frames = [f for f in traceback.extract_stack()
              if os.path.abspath(f.filename) != _THIS_FILE]
    return [f"{os.path.basename(f.filename)}:{f.lineno} in {f.name}"
            for f in frames[-8:]]


def _held_stack():
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _path_exists(src, dst):
    """DFS reachability src -> dst over the current order graph (called
    under _graph_lock)."""
    seen = set()
    todo = [src]
    while todo:
        n = todo.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        todo.extend(_adj.get(n, ()))
    return False


def _cycle_edges(src, dst):
    """One src -> dst path as edge records (called under _graph_lock);
    the reverse path of a detected cycle, for the report."""
    parent = {src: None}
    todo = [src]
    while todo:
        n = todo.pop()
        if n == dst:
            break
        for m in _adj.get(n, ()):
            if m not in parent:
                parent[m] = n
                todo.append(m)
    if dst not in parent:
        return []
    path = []
    n = dst
    while parent[n] is not None:
        path.append(_edges[(parent[n], n)])
        n = parent[n]
    return list(reversed(path))


class CheckedLock:
    """threading.Lock/RLock wrapper recording acquisition order (armed
    mode only — make_lock/make_rlock return the plain primitive when
    disarmed)."""

    def __init__(self, name, reentrant=False):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    # -- the analysis ---------------------------------------------------
    def _before_acquire(self, blocking):
        held = _held_stack()
        if any(h is self for h in held):
            if self._reentrant:
                return  # legal re-enter: no new edge, no hazard
            if blocking:
                finding = {
                    "rule": "lock-order-cycle", "kind": "self-deadlock",
                    "lock": self.name,
                    "message": f"blocking re-acquire of non-reentrant lock "
                               f"'{self.name}' on the thread that already "
                               "holds it — certain deadlock (the PR 5 "
                               "launch.py signal-handler shape)",
                    # BOTH sides: where the lock was FIRST taken (the
                    # frame the fix usually lives in) and the re-acquire
                    "stacks": {
                        "holding": list(self._acquire_stack or ()),
                        "acquiring": _stack()},
                }
                with _graph_lock:
                    _cycles.append(finding)
                raise LockOrderError(finding["message"], finding)
            return
        if not blocking:
            return  # try-lock cannot deadlock: no edge
        acq_stack = _stack()
        for h in held:
            if h.name == self.name:
                continue
            edge = (h.name, self.name)
            with _graph_lock:
                rec = _edges.get(edge)
                if rec is not None:
                    rec["count"] += 1
                    continue
                # adding h -> self creates a cycle iff self already
                # reaches h; collect the reverse path BEFORE inserting
                reverse = _cycle_edges(self.name, h.name) \
                    if _path_exists(self.name, h.name) else []
                rec = {"held": h.name, "acquired": self.name, "count": 1,
                       "held_stack": list(h._acquire_stack or ()),
                       "acquire_stack": acq_stack}
                _edges[edge] = rec
                _adj.setdefault(h.name, set()).add(self.name)
                if reverse:
                    order = " -> ".join(
                        [h.name, self.name]
                        + [e["acquired"] for e in reverse])
                    finding = {
                        "rule": "lock-order-cycle", "kind": "order-cycle",
                        "locks": [h.name, self.name],
                        "message": (
                            f"lock acquisition order cycle: '{h.name}' is "
                            f"held while acquiring '{self.name}' here, but "
                            f"elsewhere '{self.name}' is held while "
                            f"(transitively) acquiring '{h.name}' "
                            f"({order}) — the interleaved schedule "
                            "deadlocks"),
                        # BOTH acquisition stacks: this edge's, and the
                        # first reverse-path edge's (where the opposite
                        # order was taken)
                        "stacks": {
                            "forward": {"held": rec["held_stack"],
                                        "acquiring": acq_stack},
                            "reverse": {
                                "held": reverse[0]["held_stack"],
                                "acquiring": reverse[0]["acquire_stack"]},
                        },
                    }
                    _cycles.append(finding)
                    raise LockOrderError(finding["message"], finding)

    # -- lock protocol --------------------------------------------------
    _acquire_stack = None

    def acquire(self, blocking=True, timeout=-1):
        self._before_acquire(blocking)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held = _held_stack()
            if not (self._reentrant and any(h is self for h in held)):
                self._acquire_stack = _stack()
                held.append(self)
            else:
                held.append(self)   # symmetric push so release pops evenly
        return got

    def release(self):
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._inner.release()

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else any(h is self for h in _held_stack())

    def held_by_me(self):
        return any(h is self for h in _held_stack())

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        kind = "RLock" if self._reentrant else "Lock"
        return f"CheckedLock({self.name!r}, {kind})"


def make_lock(name):
    """A mutex for module `name` ('module.purpose' by convention):
    the plain threading.Lock when disarmed (zero overhead), the
    order-recording CheckedLock under MXNET_TPU_CHECK_THREADS=1."""
    return CheckedLock(name) if _armed else threading.Lock()


def make_rlock(name):
    """Reentrant variant of make_lock."""
    return CheckedLock(name, reentrant=True) if _armed else threading.RLock()


# ---------------------------------------------------------------------------
# guarded shared structures (the mutation half of tsan-lite)
# ---------------------------------------------------------------------------

class GuardedDict(dict):
    """dict whose mutations assert the guard CheckedLock is held by the
    mutating thread (armed mode). A mutation without the guard records an
    `unguarded-mutation` finding and raises LockOrderError — the CI sweep
    then fails on the new race instead of corrupting state silently."""

    def __init__(self, guard, name, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._guard = guard
        self._name = name

    def _assert_guarded(self):
        if isinstance(self._guard, CheckedLock) and self._guard.held_by_me():
            return
        finding = {
            "rule": "unguarded-mutation",
            "structure": self._name, "guard": getattr(
                self._guard, "name", str(self._guard)),
            "message": f"shared structure '{self._name}' mutated without "
                       f"holding its guard lock "
                       f"'{getattr(self._guard, 'name', self._guard)}'",
            "stack": _stack(),
        }
        with _graph_lock:
            _mutations.append(finding)
        raise LockOrderError(finding["message"], finding)

    def __setitem__(self, k, v):
        self._assert_guarded()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._assert_guarded()
        super().__delitem__(k)

    def clear(self):
        self._assert_guarded()
        super().clear()

    def pop(self, *a, **k):
        self._assert_guarded()
        return super().pop(*a, **k)

    def popitem(self):
        self._assert_guarded()
        return super().popitem()

    def setdefault(self, *a, **k):
        self._assert_guarded()
        return super().setdefault(*a, **k)

    def update(self, *a, **k):
        self._assert_guarded()
        return super().update(*a, **k)


def guarded_dict(guard, name, *args, **kwargs):
    """A dict asserting its mutations hold `guard` (armed mode); the
    plain dict when disarmed — zero overhead on the default path."""
    if _armed and isinstance(guard, CheckedLock):
        return GuardedDict(guard, name, *args, **kwargs)
    return dict(*args, **kwargs)


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def lock_graph():
    """The acquisition-order graph as plain data: every held-while-
    acquiring edge with count and both stacks."""
    with _graph_lock:
        return [dict(rec) for rec in _edges.values()]


def cycles():
    """Lock-order cycle findings recorded this process (copies)."""
    with _graph_lock:
        return [dict(c) for c in _cycles]


def unguarded_mutations():
    with _graph_lock:
        return [dict(m) for m in _mutations]


def findings():
    """All concurrency findings (cycles + unguarded mutations)."""
    with _graph_lock:
        return [dict(c) for c in _cycles] + [dict(m) for m in _mutations]
