"""mx.inspect — compiled-executable cost attribution.

`mx.telemetry` (PR 1) says how fast a run is and `mx.diagnostics` (PR 2)
says why it died; neither says whether the achieved throughput is *good*.
This module closes that gap the way XLA-era tooling does: at every jit
compile (the same cache-miss sites `gluon/block.py` and
`parallel/trainer.py` already record into the flight ring), the lowered
computation is compiled once more ANALYTICALLY — `compiled.cost_analysis()`
and `compiled.memory_analysis()` — and a per-executable `CostRecord` lands
in a registry keyed by the jit-cache signature:

  * **flops / bytes accessed** — XLA's own cost model for the whole fused
    program (the per-kernel numbers TVM-style cost models are built from);
  * **device memory** — argument / output / temp / donated bytes and the
    derived execution-time peak, knowable BEFORE the step OOMs ("Memory
    Safe Computations with XLA", PAPERS.md);
  * **MFU** — achieved FLOP/s (flops / measured step time) against a
    per-backend peak-FLOPs table (TPU generations, bf16 peaks; override
    with the `peak_flops` knob — unknown backends report null, never 0/inf);
  * **roofline** — arithmetic intensity (flops / bytes accessed) against
    the backend's peak-FLOPs/HBM-bandwidth ridge point: compute-bound vs
    memory-bound;
  * **collective traffic** — estimated bytes per psum / all-gather /
    reduce-scatter per step, computed from the sharding specs
    (`parallel/specs.py`) + mesh shape with ring-algorithm costs, giving a
    compute-vs-comm budget per executable.

Surfaced everywhere the run is already visible: telemetry gauges/counters
(`executable_flops`, `executable_peak_bytes`, `mfu_ratio`,
`collective_bytes_est{op=...}`) and `cost` events, the flight-recorder
ring + post-mortem JSON (an OOM post-mortem names the executable with the
largest `peak_bytes`), `bench.py` fields (`mfu`, `achieved_tflops`,
`peak_device_bytes`, `comm_bytes_per_step`), the "Cost & efficiency"
section of `tools/telemetry_report.py`, and the `tools/inspect_report.py`
CLI over `inspect_dir` dumps.

Cost model: DISABLED (the default) is the production fast path — every
hook site checks one module-level bool and falls through; no analysis
compile, no allocation (`ci/run.sh sanity` asserts it). ENABLED costs one
extra lower+compile per jit-cache miss (served warm from the persistent
XLA cache when `compile_cache_dir` is set) and a per-step fence in the
trainers so recorded step time is device time. Backends that return
partial or no cost analysis (CPU reports flops but little else) degrade
to null fields, never a crash.
"""
from __future__ import annotations

import atexit
import json
import os
import time

from . import _locklint
from . import config
from . import diagnostics as _diagnostics
from . import telemetry as _telemetry

__all__ = [
    "enable", "disable", "enabled", "reset",
    "CostRecord", "analyze_jit", "record_compiled", "note_step",
    "records", "get", "snapshot", "summary", "dump", "memory_breakdown",
    "peak_flops_per_chip", "peak_bandwidth_per_chip",
    "estimate_collectives", "key_repr",
]

_lock = _locklint.make_rlock("inspect.registry")
_enabled = False                  # the fast-path bool; see enable()/disable()
# plain dict when tsan-lite is off; armed, every mutation asserts _lock
# is held (the shared-structure half of the mx.check concurrency sweep)
_registry = _locklint.guarded_dict(_lock, "inspect.registry")
# (name, key) -> CostRecord
_last_live_dump = 0.0
_LIVE_DUMP_INTERVAL = 30.0        # seconds between inspect_dir refreshes

# Per-chip bf16 peak FLOP/s and HBM bandwidth by TPU generation (matched
# against device_kind substrings, most specific first). Published nominal
# numbers; the `peak_flops` knob overrides when the workload is not bf16
# or the table is stale for a new generation.
_PEAK_FLOPS_TABLE = (
    ("v6", 918e12), ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5", 459e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)
_PEAK_BW_TABLE = (
    ("v6", 1640e9), ("v5p", 2765e9), ("v5 lite", 819e9), ("v5e", 819e9),
    ("v5", 2765e9), ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)

# memory-bound remediation hints: the applicable mx.kernels entry by
# executable-name fragment, most specific first (mirrors how mx.check's
# degenerate-sharding rule names mx.zero — a verdict should carry the
# fix that exists in-tree, not just the diagnosis). Surfaced in
# as_dict()/tools/inspect_report.py whenever roofline says memory-bound.
_KERNEL_HINTS = (
    ("moe", "pallas_ops.moe_kernels (kernels=auto): fused MoE "
            "dispatch/combine without the (N,E,C) one-hot tensor"),
    ("decode", "pallas_ops.int8_matmul via "
               "contrib.quantization.quantize_block (kernels=auto): "
               "int8 decode matmuls with the per-channel rescale fused"),
    ("serve", "pallas_ops.int8_matmul via "
              "contrib.quantization.quantize_block (kernels=auto): "
              "int8 decode matmuls with the per-channel rescale fused"),
    ("generate", "pallas_ops.int8_matmul via "
                 "contrib.quantization.quantize_block (kernels=auto): "
                 "int8 decode matmuls with the per-channel rescale "
                 "fused"),
    ("step", "pallas_ops.fused_update (kernels=auto): one-VMEM-pass "
             "optimizer update instead of the elementwise HLO chain"),
    ("train", "pallas_ops.fused_update (kernels=auto): one-VMEM-pass "
              "optimizer update instead of the elementwise HLO chain"),
)
_KERNEL_HINT_DEFAULT = (
    "mx.kernels (pallas_ops/): a hand-scheduled Pallas kernel can beat "
    "the generic lowering where the roofline says memory-bound — see "
    "README 'Kernel library'")

# telemetry series (get-or-create; updates are no-ops while telemetry is
# disabled, so inspect-without-telemetry costs nothing here)
_M_EXEC_FLOPS = _telemetry.gauge(
    "executable_flops", "XLA cost-model flops of one compiled executable "
    "(labeled by executable name)")
_M_EXEC_PEAK = _telemetry.gauge(
    "executable_peak_bytes", "estimated peak device bytes resident while "
    "one compiled executable runs (arguments + outputs + temps - donated)")
_M_MFU = _telemetry.gauge(
    "mfu_ratio", "achieved FLOP/s over per-chip peak for one executable "
    "(null-backed: stays unset when peak flops is unknown)")
_M_COLL_EST = _telemetry.counter(
    "collective_bytes_est", "estimated collective payload bytes per "
    "executed step, from sharding specs + mesh shape (ring-algorithm "
    "per-device cost), labeled by collective op")


def enabled():
    """True when cost attribution is on (hook sites read the module global
    `_enabled` directly — this accessor is the public spelling)."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def reset():
    """Drop every CostRecord (tests and run boundaries; the cached
    device-kind lookup drops too, for tests that swap backends)."""
    global _kind_cache
    with _lock:
        _registry.clear()
        _kind_cache = None


# ---------------------------------------------------------------------------
# backend peaks
# ---------------------------------------------------------------------------

_kind_cache = None                # device_kind can't change mid-process


def _device_kind():
    """device_kind of the first local device, '' when no backend is
    initialized yet (never cold-inits a backend — same rule as the
    diagnostics memory poll). Cached after the first successful lookup:
    note_step's mfu gauge would otherwise hit jax.local_devices() on
    every fenced step."""
    global _kind_cache
    if _kind_cache is not None:
        return _kind_cache
    devs = _diagnostics._jax_devices_if_initialized()
    if not devs:
        return ""
    _kind_cache = str(getattr(devs[0], "device_kind", ""))
    return _kind_cache


def _table_lookup(table, kind):
    kind = kind.lower()
    for frag, value in table:
        if frag in kind:
            return value
    return None


def peak_flops_per_chip():
    """Per-chip peak FLOP/s: the `peak_flops` knob when set, else the TPU
    generation table by device_kind, else None (CPU and unknown backends:
    MFU is then reported null)."""
    knob = float(config.get("peak_flops"))
    if knob > 0:
        return knob
    return _table_lookup(_PEAK_FLOPS_TABLE, _device_kind())


def peak_bandwidth_per_chip():
    """Per-chip HBM bandwidth (bytes/s) from the generation table, None
    when unknown — the roofline ridge point needs both peaks."""
    return _table_lookup(_PEAK_BW_TABLE, _device_kind())


# ---------------------------------------------------------------------------
# collective-traffic estimate
# ---------------------------------------------------------------------------

def estimate_collectives(mesh, sized_shardings, zero=None):
    """Estimated collective payload bytes per train step for one
    executable, from its parameter shardings + mesh shape.

    `sized_shardings`: [(nbytes, sharding_or_spec), ...] for every trained
    parameter. Ring-algorithm per-device costs: all-reduce moves
    2*(n-1)/n of the payload, all-gather and reduce-scatter (n-1)/n.
    Model: replicated params all-reduce (psum) their gradient over the
    data axes; fsdp-sharded params all-gather before use and
    reduce-scatter the gradient over fsdp, then all-reduce the shard over
    dp. `zero`: optional per-entry bools — a mx.zero'd parameter's
    would-be gradient psum is replaced by the reduce-scatter(grad) +
    all-gather(updated param) pair the zero step actually runs: the SAME
    ring bytes ((n-1)/n each way vs 2*(n-1)/n), attributed to the real
    ops. Tensor-parallel activation collectives are not modeled — this is
    the data-parallel budget, labeled an estimate everywhere it surfaces.
    Returns {} when no data axis spans more than one device."""
    dp = int(mesh.shape.get("dp", 1))
    fsdp = int(mesh.shape.get("fsdp", 1))
    n = dp * fsdp
    if n <= 1:
        return {}
    out = {"psum": 0.0, "all_gather": 0.0, "reduce_scatter": 0.0}

    def _reduce(nbytes, degree, zeroed):
        # one gradient reduction over `degree` devices: psum classically,
        # the rs/ag split (half the 2(n-1)/n each) when zero'd
        cost = 2.0 * (degree - 1) / degree * nbytes
        if zeroed:
            out["reduce_scatter"] += cost / 2.0
            out["all_gather"] += cost / 2.0
        else:
            out["psum"] += cost

    for i, (nbytes, sharding) in enumerate(sized_shardings):
        nbytes = float(nbytes)
        zeroed = bool(zero[i]) if zero else False
        spec = getattr(sharding, "spec", sharding)
        axes = set()
        for entry in (spec or ()):
            if entry is None:
                continue
            axes.update(entry if isinstance(entry, tuple) else (entry,))
        if "fsdp" in axes and fsdp > 1:
            out["all_gather"] += (fsdp - 1) / fsdp * nbytes
            out["reduce_scatter"] += (fsdp - 1) / fsdp * nbytes
            if dp > 1:
                _reduce(nbytes / fsdp, dp, zeroed)
        else:
            _reduce(nbytes, n, zeroed)
    return {k: int(v) for k, v in out.items() if v > 0}


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

def key_repr(key):
    """Stable string form of a jit-cache key (the registry key component).
    repr() is deterministic for the shape/dtype/flag tuples the caches
    use; anything unhashable upstream never reaches a cache anyway."""
    return repr(key)


class CostRecord:
    """Cost attribution for ONE compiled executable: XLA cost/memory
    analysis captured at compile time plus step-time accounting fed from
    the trainer. All analysis fields are None when the backend did not
    report them."""

    def __init__(self, name, key):
        self.name = name
        self.key = key
        self.created = time.time()
        self.compiles = 0
        self.flops = None             # XLA cost-model flops per execution
        self.bytes_accessed = None    # HBM bytes touched per execution
        self.argument_bytes = None
        self.output_bytes = None
        self.temp_bytes = None
        self.donated_bytes = None     # alias/donation savings
        self.peak_bytes = None        # args + outputs + temps - donated
        self.generated_code_bytes = None
        self.collectives = {}         # op -> estimated bytes per step
        self.steps = 0
        self.step_time_s = 0.0
        self.analysis_error = None    # str when cost/memory analysis failed

    # -- derived metrics ------------------------------------------------
    def avg_step_s(self):
        return self.step_time_s / self.steps if self.steps else None

    def achieved_flops(self):
        """Achieved FLOP/s over measured step time (None until both the
        cost analysis and at least one timed step exist)."""
        avg = self.avg_step_s()
        if self.flops is None or not avg:
            return None
        return self.flops / avg

    def mfu(self, peak=None):
        """Achieved/peak FLOP/s; None (never 0 or inf) when either the
        achieved rate or the per-chip peak is unknown."""
        ach = self.achieved_flops()
        peak = peak if peak is not None else peak_flops_per_chip()
        if ach is None or not peak:
            return None
        return ach / peak

    def arithmetic_intensity(self):
        """flops per byte accessed (the roofline x-axis)."""
        if not self.flops or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    def roofline(self, peak=None, bandwidth=None):
        """'compute-bound' or 'memory-bound' against the backend ridge
        point (peak flops / HBM bandwidth); None when any input is
        unknown."""
        ai = self.arithmetic_intensity()
        peak = peak if peak is not None else peak_flops_per_chip()
        bandwidth = bandwidth if bandwidth is not None \
            else peak_bandwidth_per_chip()
        if ai is None or not peak or not bandwidth:
            return None
        return "compute-bound" if ai >= peak / bandwidth else "memory-bound"

    def comm_bytes_per_step(self):
        return sum(self.collectives.values()) if self.collectives else None

    def kernel_hint(self):
        """The mx.kernels remediation for a memory-bound executable:
        which pallas_ops kernel applies, matched on the executable name
        (None unless the roofline verdict is memory-bound)."""
        if self.roofline() != "memory-bound":
            return None
        name = (self.name or "").lower()
        for frag, hint in _KERNEL_HINTS:
            if frag in name:
                return hint
        return _KERNEL_HINT_DEFAULT

    def as_dict(self):
        d = {
            "name": self.name, "key": self.key, "created": self.created,
            "compiles": self.compiles, "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "donated_bytes": self.donated_bytes,
            "peak_bytes": self.peak_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "collectives": dict(self.collectives),
            "comm_bytes_per_step": self.comm_bytes_per_step(),
            "steps": self.steps,
            "step_time_s": round(self.step_time_s, 6),
            "avg_step_s": self.avg_step_s(),
            "achieved_flops": self.achieved_flops(),
            "mfu": self.mfu(),
            "arithmetic_intensity": self.arithmetic_intensity(),
            "roofline": self.roofline(),
            "kernel_hint": self.kernel_hint(),
        }
        if self.analysis_error:
            d["analysis_error"] = self.analysis_error
        return d


def _get_record(name, key):
    with _lock:
        rec = _registry.get((name, key))
        if rec is None:
            rec = CostRecord(name, key)
            _registry[(name, key)] = rec
        return rec


def memory_breakdown(mem):
    """(argument, output, temp, alias, peak) bytes from one
    CompiledMemoryStats — peak is the derived execution-time resident
    estimate (args + outputs + temps - donated), None when any component
    is missing. Shared with mx.memsafe so the pre-flight budget check and
    this registry can never account differently."""
    if mem is None:
        return None, None, None, None, None
    arg = getattr(mem, "argument_size_in_bytes", None)
    out = getattr(mem, "output_size_in_bytes", None)
    tmp = getattr(mem, "temp_size_in_bytes", None)
    alias = getattr(mem, "alias_size_in_bytes", None)
    peak = None
    if None not in (arg, out, tmp):
        peak = arg + out + tmp - (alias or 0)
    return arg, out, tmp, alias, peak


def _first_dict(analysis):
    """cost_analysis() returns a dict on newer jax, a list of per-module
    dicts on older; normalize to the entry computation's dict ({} when
    absent or unrecognizable)."""
    if isinstance(analysis, dict):
        return analysis
    if isinstance(analysis, (list, tuple)) and analysis \
            and isinstance(analysis[0], dict):
        return analysis[0]
    return {}


def record_compiled(name, key, compiled, collectives=None):
    """Attribute one compiled executable: run cost_analysis() /
    memory_analysis() defensively (partial or raising backends degrade to
    null fields) and fold the result into the registry, the telemetry
    gauges + `cost` event, and the diagnostics flight ring. Returns the
    CostRecord. Never raises."""
    rec = _get_record(name, key)
    errors = []
    cost = {}
    try:
        cost = _first_dict(compiled.cost_analysis())
    except Exception as e:
        errors.append(f"cost_analysis: {type(e).__name__}: {e}")
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception as e:
        errors.append(f"memory_analysis: {type(e).__name__}: {e}")
    with _lock:
        rec.compiles += 1
        if "flops" in cost:
            rec.flops = float(cost["flops"])
        if "bytes accessed" in cost:
            rec.bytes_accessed = float(cost["bytes accessed"])
        if mem is not None:
            arg, out, tmp, alias, peak = memory_breakdown(mem)
            rec.argument_bytes = arg
            rec.output_bytes = out
            rec.temp_bytes = tmp
            rec.donated_bytes = alias
            rec.generated_code_bytes = getattr(
                mem, "generated_code_size_in_bytes", None)
            if peak is not None:
                rec.peak_bytes = peak
        if collectives:
            rec.collectives = dict(collectives)
        if errors:
            rec.analysis_error = "; ".join(errors)
    if _telemetry._enabled:
        if rec.flops is not None:
            _M_EXEC_FLOPS.labels(executable=name).set(rec.flops)
        if rec.peak_bytes is not None:
            _M_EXEC_PEAK.labels(executable=name).set(rec.peak_bytes)
        _telemetry.event(
            "cost", executable=name, key=key, flops=rec.flops,
            bytes_accessed=rec.bytes_accessed, peak_bytes=rec.peak_bytes,
            argument_bytes=rec.argument_bytes,
            output_bytes=rec.output_bytes, temp_bytes=rec.temp_bytes,
            donated_bytes=rec.donated_bytes,
            collectives=dict(rec.collectives),
            peak_flops=peak_flops_per_chip(),
            peak_bandwidth=peak_bandwidth_per_chip(),
            backend=_device_kind() or None)
    if _diagnostics._enabled:
        # the ring entry makes shape-churn-into-OOM diagnosable: a
        # post-mortem whose last compiles show growing peak_bytes is the
        # smoking gun
        _diagnostics.record_event(
            "cost", executable=name, flops=rec.flops,
            peak_bytes=rec.peak_bytes, bytes_accessed=rec.bytes_accessed)
    return rec


def analyze_jit(name, key, jitted, *args, collectives=None):
    """Lower + compile `jitted` at `args`' signature purely for analysis
    and record the result (the execution path keeps its own lazily
    compiled executable — with `compile_cache_dir` set the second compile
    deserializes from the persistent cache instead of rebuilding).
    Returns the CostRecord, or one with an analysis_error when the
    backend cannot lower/compile out-of-line. Never raises."""
    if not _enabled:
        return None
    try:
        compiled = jitted.lower(*args).compile()
    except Exception as e:
        rec = _get_record(name, key)
        with _lock:
            rec.compiles += 1
            rec.analysis_error = f"lower/compile: {type(e).__name__}: {e}"
            if collectives:
                rec.collectives = dict(collectives)
        return rec
    return record_compiled(name, key, compiled, collectives=collectives)


def note_step(name, key, dur_s):
    """Fold one measured step execution into the executable's record:
    step count + wall time (the MFU denominator), the mfu_ratio gauge,
    and the per-op collective_bytes_est counters. Hook sites guard on
    `_enabled` themselves; this re-checks for direct callers."""
    if not _enabled:
        return
    with _lock:
        rec = _registry.get((name, key))
        if rec is None:
            return
        rec.steps += 1
        rec.step_time_s += float(dur_s)
    if _telemetry._enabled:
        m = rec.mfu()
        if m is not None:
            _M_MFU.labels(executable=name).set(m)
        for op, nbytes in rec.collectives.items():
            _M_COLL_EST.labels(op=op).inc(nbytes)
    _maybe_live_dump()


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def records():
    """All CostRecords, insertion-ordered."""
    with _lock:
        return list(_registry.values())


def get(name, key=None):
    """The CostRecord for `name` (+ `key` when several signatures exist);
    None when absent."""
    with _lock:
        if key is not None:
            return _registry.get((name, key))
        for (n, _), rec in _registry.items():
            if n == name:
                return rec
    return None


def snapshot():
    """The registry as plain data (what dump() writes and the post-mortem
    embeds): backend + peaks, every record, and the executable with the
    largest peak_bytes — the first thing to read after an OOM."""
    with _lock:
        recs = [r.as_dict() for r in _registry.values()]
    largest = None
    best = -1
    for r in recs:
        if r["peak_bytes"] is not None and r["peak_bytes"] > best:
            best, largest = r["peak_bytes"], r["name"]
    return {
        "backend": _device_kind() or None,
        "peak_flops_per_chip": peak_flops_per_chip(),
        "peak_bandwidth_per_chip": peak_bandwidth_per_chip(),
        "largest_peak_bytes_executable": largest,
        "records": recs,
    }


def summary():
    """Headline efficiency numbers for the hottest executable (most flops
    among those with timed steps, else most flops overall): the dict
    bench.py folds into its JSON line. All values nullable; {} when the
    registry is empty."""
    with _lock:
        recs = list(_registry.values())
    timed = [r for r in recs if r.steps and r.flops is not None] or \
        [r for r in recs if r.flops is not None] or recs
    if not timed:
        return {}
    rec = max(timed, key=lambda r: r.flops or 0.0)
    ach = rec.achieved_flops()
    return {
        "executable": rec.name,
        "flops": rec.flops,
        "mfu": rec.mfu(),
        "achieved_tflops": ach / 1e12 if ach is not None else None,
        "peak_device_bytes": rec.peak_bytes,
        "comm_bytes_per_step": rec.comm_bytes_per_step(),
        "arithmetic_intensity": rec.arithmetic_intensity(),
        "roofline": rec.roofline(),
    }


def _default_dump_path():
    d = config.get("inspect_dir")
    if not d:
        return None
    return os.path.join(d, str(_diagnostics._rank()), "inspect.json")


def dump(path=None):
    """Write snapshot() as JSON to `path` (default:
    inspect_dir/<rank>/inspect.json — the file tools/inspect_report.py
    reads). Returns the path, or None when there is no target."""
    path = path or _default_dump_path()
    if not path:
        return None
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snapshot(), f, default=str)
    os.replace(tmp, path)  # readers (live report) never see a torn file
    return path


def _maybe_live_dump():
    """Periodic inspect_dir refresh so the report CLI can watch a live
    run; rate-limited, and any write failure is swallowed (attribution
    must never kill the step it is observing)."""
    global _last_live_dump
    if not config.get("inspect_dir"):
        return
    now = time.monotonic()
    if now - _last_live_dump < _LIVE_DUMP_INTERVAL:
        return
    _last_live_dump = now
    try:
        dump()
    except OSError:
        pass


@atexit.register
def _dump_at_exit():
    if not _enabled or not config.get("inspect_dir"):
        return
    try:
        dump()
    except OSError:
        pass    # nothing useful to do with a write error at interpreter exit


if config.get("inspect"):
    enable()
