"""mx.scope — live per-rank introspection endpoints and on-demand device
profiling.

Every observability layer so far is post-hoc: telemetry flushes JSONL,
diagnostics writes post-mortems, inspect/trace dump files a report CLI
reads after the run. A production gang serving live traffic needs its
state *queryable while running* — Prometheus pull scrapes, liveness
probes, and the ability to trigger an XLA device profile on a live gang
without restarting it. This module is that control plane: a stdlib-only
(`http.server`) per-rank HTTP server exposing

  * ``/healthz``  — rank liveness: pid, current step, seconds since the
    last completed step, and the mx.guard heartbeat age when guard is
    armed. The process answering IS the liveness signal; readers judge
    staleness from the ages.
  * ``/metrics``  — the full mx.telemetry registry in Prometheus text
    exposition format, rendered by ``telemetry.dump_prometheus``'s
    renderer (never through a file): the whole tree renders under the
    registry lock, so a scrape mid-``Histogram.observe`` can never see a
    torn bucket set (the PR 4 atomic-dumps guarantee, extended to HTTP).
  * ``/statusz``  — one JSON gang-member view: current step + step rate,
    the diagnostics flight-ring tail, mx.memsafe headroom and the active
    remat/zero/grad-accum rungs, ``serve.Server.stats()`` for every live
    server, the mx.trace skew verdict + suspected straggler, and the
    supervised-relaunch generation.
  * ``/tracez``   — the last N buffered mx.trace spans + skew probes.
  * ``/profilez?steps=N`` — on-demand XLA device capture: arms
    ``profiler.start_jax_trace``/``stop_jax_trace`` around the next N
    trainer steps via the existing step-hook site (the capture starts
    and stops at step boundaries ON the trainer thread — training is
    never paused or reordered) and returns the trace directory path.
    A second request while one capture is armed/active gets 409.

Gang layer: ``tools/launch.py --scope-port P`` gives rank R the port
``P + 1 + R`` and serves an aggregator on the base port ``P`` that fans
out to the per-rank endpoints with short timeouts (a wedged rank can
never wedge the aggregator), merges ``/statusz`` into one gang view
naming stale/unreachable ranks, and proxies ``/profilez`` to every rank
at once for a gang-wide capture. ``tools/scope_top.py`` polls the
aggregator and renders a live one-screen gang summary.

Cost model: DISABLED (the default) is the production fast path — the
trainer hook site checks one module-level bool and falls through; no
thread runs, no socket listens, nothing allocates (``ci/run.sh sanity``
asserts this). Enable with ``mx.scope.enable()`` / ``MXNET_TPU_SCOPE=on``
/ ``tools/launch.py --scope-port``. The server binds 127.0.0.1 by
default (pass ``host=`` to expose it beyond the machine).
"""
from __future__ import annotations

import collections
import json
import os
import sys
import tempfile
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from . import _locklint
from . import config as _config
from . import diagnostics as _diagnostics
from . import guard as _guard
from . import profiler as _profiler
from . import telemetry as _telemetry
from . import trace as _trace

__all__ = [
    "enable", "disable", "enabled", "reset", "maybe_enable",
    "on_step", "port", "url",
    "healthz", "statusz", "tracez", "request_profile", "profile_status",
    "ProfileBusy", "ScopeServer", "ScopeState",
]

_lock = _locklint.make_lock("scope.state")
_enabled = False          # the fast-path bool; the trainer hook reads it
_state = None             # ScopeState; None while disabled (zero-alloc)
_server = None            # ScopeServer; None while disabled (zero threads)

# how many ring / span records the JSON endpoints return by default
# (bounded responses: a scrape must stay cheap whatever the buffers hold)
_RING_TAIL = 8
_TRACEZ_SPANS = 64
_PROFILE_MAX_STEPS = 10_000
_RATE_WINDOW = 64         # (monotonic, step) samples for steps/s


class ProfileBusy(RuntimeError):
    """A /profilez capture is already armed or active (HTTP 409)."""

    def __init__(self, existing):
        self.existing = existing
        super().__init__(
            "a device-profile capture is already "
            f"{existing.get('state')} (trace_dir {existing.get('dir')!r})")


def _rank_from_env():
    for var in ("JAX_PROCESS_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _generation():
    try:
        return int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    except ValueError:
        return 0


class ScopeState:
    """Per-rank introspection state: the last completed step, a bounded
    step-rate window, and the single armed/active profile capture. One
    module singleton in production; tests instantiate several (one per
    simulated rank) to exercise the aggregator in-process."""

    def __init__(self, rank=None):
        self.rank_override = rank
        self.started_wall = time.time()
        self.last_step = None
        self.last_step_mono = None
        self.last_step_wall = None
        self._rate = collections.deque(maxlen=_RATE_WINDOW)
        self._trainer = None      # weakref to the last stepping trainer
        self.profile = None       # the single capture slot (see on_step)
        self._lock = _locklint.make_lock("scope.instance")

    def rank(self):
        return self.rank_override if self.rank_override is not None \
            else _rank_from_env()

    # -- trainer hook ----------------------------------------------------
    def note_step(self, trainer, step):
        """Record one completed trainer step (hot path while enabled:
        a few attribute writes, no locks unless a capture is live)."""
        now = time.monotonic()
        self.last_step = int(step)
        self.last_step_mono = now
        self.last_step_wall = time.time()
        if trainer is not None and (self._trainer is None
                                    or self._trainer() is not trainer):
            # re-ref only on trainer change: a fresh weakref per step
            # would be an allocation on the hot path
            self._trainer = weakref.ref(trainer)
        rate = self._rate
        if not rate or now - rate[-1][0] >= 0.25:
            rate.append((now, int(step)))
        p = self.profile
        if p is not None and p["state"] != "done":
            self._profile_tick(p, int(step))

    def steps_per_s(self):
        rate = list(self._rate)
        if len(rate) < 2:
            return None
        (t0, s0), (t1, s1) = rate[0], rate[-1]
        if t1 <= t0 or s1 < s0:
            return None
        return round((s1 - s0) / (t1 - t0), 3)

    def trainer(self):
        ref = self._trainer
        return ref() if ref is not None else None

    # -- on-demand device profiling --------------------------------------
    def request_profile(self, steps, trace_dir=None):
        """Arm one XLA device capture around the NEXT `steps` trainer
        steps. Returns the capture record (its 'done' event is set when
        the trainer-thread hook stops the trace). Raises ProfileBusy when
        a capture is already armed or active — concurrent captures would
        corrupt jax.profiler's single global trace session."""
        steps = int(steps)
        if not 1 <= steps <= _PROFILE_MAX_STEPS:
            raise ValueError(
                f"profilez steps must be in [1, {_PROFILE_MAX_STEPS}], "
                f"got {steps}")
        with self._lock:
            p = self.profile
            if p is not None and p["state"] != "done":
                raise ProfileBusy(p)
            d = str(trace_dir) if trace_dir else tempfile.mkdtemp(
                prefix=f"mx_scope_profile_r{self.rank()}_")
            rec = {"dir": d, "steps": steps, "state": "armed",
                   "requested_ts": time.time(), "start_step": None,
                   "end_step": None, "error": None,
                   "done": threading.Event()}
            self.profile = rec
        return rec

    def _profile_tick(self, p, step):
        """Drive the armed capture from the trainer thread at the step
        boundary: start the trace after the arming step completes (the
        capture covers the next `steps` full steps), stop it once they
        have. start/stop run HERE — never on an HTTP thread — so the
        jax.profiler session start/stop can never race a dispatching
        step, and training order is untouched."""
        with self._lock:
            if p is not self.profile or p["state"] == "done":
                return
            if p["state"] == "armed":
                try:
                    os.makedirs(p["dir"], exist_ok=True)
                    _profiler.start_jax_trace(p["dir"])
                    p["state"] = "active"
                    p["start_step"] = step
                except Exception as e:  # noqa: BLE001 - reported, not fatal
                    p["state"] = "done"
                    p["error"] = f"{type(e).__name__}: {e}"
                    p["done"].set()
                return
            if p["state"] == "active" and step >= p["start_step"] + p["steps"]:
                try:
                    _profiler.stop_jax_trace()
                except Exception as e:  # noqa: BLE001 - reported, not fatal
                    p["error"] = f"{type(e).__name__}: {e}"
                p["state"] = "done"
                p["end_step"] = step
                p["done"].set()

    def abort_profile(self):
        """Stop a live capture (disable()/server shutdown): an armed one
        is cancelled, an active one stops its jax trace so the profiler
        session is never left dangling."""
        with self._lock:
            p, self.profile = self.profile, None
        if p is None or p["state"] == "done":
            return
        if p["state"] == "active":
            try:
                _profiler.stop_jax_trace()
            except Exception:
                pass
        p["state"] = "done"
        p["error"] = p["error"] or "aborted"
        p["done"].set()

    def profile_status(self):
        with self._lock:
            p = self.profile
            if p is None:
                return None
            return {k: p[k] for k in ("dir", "steps", "state",
                                      "start_step", "end_step", "error",
                                      "requested_ts")}


# ---------------------------------------------------------------------------
# endpoint payload builders (pure functions of a ScopeState — the HTTP
# handler and tests share them)
# ---------------------------------------------------------------------------

def _step_age_s(state):
    if state.last_step_mono is None:
        return None
    return round(time.monotonic() - state.last_step_mono, 3)


def healthz(state=None):
    """Liveness payload: the process answering is the liveness signal;
    the ages let a reader (the gang aggregator, a k8s probe) judge
    staleness without a clock exchange."""
    state = state or _state
    if state is None:
        return {"ok": False, "error": "scope disabled"}
    hb = _guard.last_heartbeat() if _guard._enabled else None
    return {
        "ok": True,
        "rank": state.rank(),
        "pid": os.getpid(),
        "ts": time.time(),
        "generation": _generation(),
        "step": state.last_step,
        "last_step_age_s": _step_age_s(state),
        "heartbeat_age_s": _guard.heartbeat_age_s() if hb else None,
        "heartbeat_phase": hb.get("phase") if hb else None,
        "uptime_s": round(time.time() - state.started_wall, 3),
    }


def _memsafe_section():
    ms = sys.modules.get(__package__ + ".memsafe")
    if ms is None:
        return None
    try:
        last = ms.last_check()
        out = {"headroom_bytes": ms.last_headroom_bytes(),
               "oom_events": ms._oom_events,
               "transitions": ms.transitions()[-4:]}
        if last:
            out["last_check"] = {k: last.get(k) for k in
                                 ("executable", "predicted_bytes",
                                  "capacity_bytes", "headroom_bytes")}
        return out
    except Exception as e:  # noqa: BLE001 - a section must not kill statusz
        return {"error": str(e)}


def _rungs_section(state):
    tr = state.trainer()
    if tr is None:
        return None
    out = {"grad_accum": getattr(tr, "_accum", None),
           "zero": bool(getattr(tr, "_zero", False)),
           "param_mode": getattr(tr, "param_mode", None)}
    ms = sys.modules.get(__package__ + ".memsafe")
    if ms is not None:
        try:
            out["remat_policy"] = ms.policy_marker(tr.block)
        except Exception:
            pass
    return out


def _serve_section():
    sv = sys.modules.get(__package__ + ".serve")
    if sv is None:
        return None
    try:
        servers = sv.servers()
    except Exception:
        return None
    if not servers:
        return None
    out = {"servers": [s.stats() for s in servers]}
    try:
        h = _telemetry.get("serve_ttft_seconds")
        if h.count:
            out["ttft_p50_ms"] = round((h.percentile(50) or 0) * 1e3, 3)
            out["ttft_p99_ms"] = round((h.percentile(99) or 0) * 1e3, 3)
    except KeyError:
        pass
    return out


def _fleet_section():
    fl = sys.modules.get(__package__ + ".fleet")
    if fl is None or not fl._enabled:
        return None
    try:
        return fl.snapshot()
    except Exception:
        return None


def _goodput_section():
    gp = sys.modules.get(__package__ + ".goodput")
    if gp is None or not gp._enabled:
        return None
    try:
        return gp.snapshot()
    except Exception:
        return None


def _slo_section():
    sl = sys.modules.get(__package__ + ".slo")
    if sl is None or not sl._enabled:
        return None
    try:
        return sl.snapshot()
    except Exception as e:  # noqa: BLE001 - a section must not kill statusz
        return {"error": str(e)}


def statusz(state=None):
    """The one-rank gang-member view the aggregator merges: step +
    rate, flight-ring tail, memory headroom and active degradation
    rungs, live serve stats, skew verdict, restart generation. Every
    section degrades to None/error independently — a broken subsystem
    must not take the whole status page with it."""
    state = state or _state
    if state is None:
        return {"ok": False, "error": "scope disabled"}
    out = healthz(state)
    out["steps_per_s"] = state.steps_per_s()
    out["ring_tail"] = _diagnostics.ring_tail(_RING_TAIL)
    out["memsafe"] = _memsafe_section()
    out["rungs"] = _rungs_section(state)
    out["serve"] = _serve_section()
    out["fleet"] = _fleet_section()
    out["slo"] = _slo_section()
    out["goodput"] = _goodput_section()
    out["trace"] = _trace.skew_verdict()
    out["guard"] = _guard.snapshot() if _guard._enabled else None
    out["profile"] = state.profile_status()
    out["telemetry_enabled"] = _telemetry._enabled
    res = sys.modules.get(__package__ + ".resilience")
    if res is not None:
        try:
            out["resume"] = res.last_resume()
        except Exception:
            pass
    return out


def tracez(state=None, n=_TRACEZ_SPANS):
    state = state or _state
    # n <= 0 means "no spans", never "all of them" — and the copy
    # itself is bounded via spans(tail=): a scrape must not duplicate a
    # 100k-record buffer under the trace recorder's hot-path lock
    n = max(0, int(n))
    return {
        "rank": state.rank() if state else _rank_from_env(),
        "enabled": _trace._enabled,
        "spans_buffered": _trace.snapshot()["spans_buffered"],
        "spans": _trace.spans(tail=n),
        "skews": _trace.skews()[-16:],
    }


def request_profile(steps, trace_dir=None):
    """Module-level spelling of ScopeState.request_profile (the enabled
    singleton)."""
    if _state is None:
        raise RuntimeError("mx.scope is disabled — enable() first")
    return _state.request_profile(steps, trace_dir=trace_dir)


def profile_status():
    return _state.profile_status() if _state is not None else None


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    # scrape traffic must not spam worker stdout (the launcher prefixes
    # and tees every line) — errors surface through response codes
    def log_message(self, *args):
        pass

    def _send(self, code, payload, content_type="application/json"):
        body = payload if isinstance(payload, bytes) else \
            json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler spelling
        state = self.server._scope_state
        parts = urlsplit(self.path)
        route = parts.path.rstrip("/") or "/"
        q = parse_qs(parts.query)
        try:
            if route == "/healthz":
                self._send(200, healthz(state))
            elif route == "/metrics":
                text = _telemetry.dump_prometheus()
                self._send(200, text.encode(),
                           content_type=_telemetry.PROM_CONTENT_TYPE)
            elif route == "/statusz":
                self._send(200, statusz(state))
            elif route == "/tracez":
                n = int(q.get("n", [_TRACEZ_SPANS])[0])
                self._send(200, tracez(state, n=n))
            elif route == "/profilez":
                self._profilez(state, q)
            elif route == "/":
                self._send(200, {
                    "rank": state.rank(),
                    "endpoints": ["/healthz", "/metrics", "/statusz",
                                  "/tracez", "/profilez?steps=N"]})
            else:
                self._send(404, {"error": f"no such endpoint {route!r}"})
        except BrokenPipeError:
            pass       # client went away mid-response
        except ValueError as e:
            # malformed query values (n=abc, wait_s=abc): client error
            try:
                self._send(400, {"error": str(e)})
            except OSError:
                pass
        except Exception as e:  # noqa: BLE001 - a scrape must not kill the server
            try:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass

    def _profilez(self, state, q):
        """steps=N arms a capture (409 while one is live) and blocks up
        to wait_s for the trainer-thread hook to complete it; without
        steps=, reports the current capture state (poll target)."""
        if "steps" not in q:
            st = state.profile_status()
            self._send(200 if st else 404,
                       st or {"error": "no capture requested yet "
                                       "(GET /profilez?steps=N)"})
            return
        wait_s = float(q.get("wait_s", ["60"])[0])
        try:
            rec = state.request_profile(int(q["steps"][0]),
                                        trace_dir=(q.get("dir") or
                                                   [None])[0])
        except ProfileBusy as e:
            self._send(409, {"error": str(e), "profile": e.existing and {
                k: e.existing.get(k) for k in ("dir", "steps", "state")}})
            return
        except ValueError as e:
            self._send(400, {"error": str(e)})
            return
        completed = rec["done"].wait(wait_s) if wait_s > 0 else False
        # answer from THIS request's capture record, not the current
        # slot: a new capture armed (or a disable()) during the wait
        # must not swap another capture's dir/state into this response
        with state._lock:
            st = {k: rec[k] for k in ("dir", "steps", "state",
                                      "start_step", "end_step", "error",
                                      "requested_ts")}
        st["completed"] = bool(completed)
        if completed and st.get("error"):
            self._send(500, st)
        else:
            # 202: armed/active — the capture finishes when the trainer
            # steps; poll GET /profilez (no steps) for completion
            self._send(200 if completed else 202, st)


class ScopeServer:
    """One rank's introspection HTTP server (a daemon-threaded
    ThreadingHTTPServer — slow scrapes never serialize behind each
    other, and a blocked /profilez wait never blocks /healthz)."""

    def __init__(self, state, port=0, host="127.0.0.1"):
        self.state = state
        self.httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.httpd.daemon_threads = True
        self.httpd._scope_state = state
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="mx-scope-server", daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self.httpd.server_address[1]

    @property
    def url(self):
        host = self.httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# module lifecycle
# ---------------------------------------------------------------------------

def enabled():
    """True when the introspection server is up (the trainer hook reads
    the module global `_enabled` directly — this is the public
    spelling)."""
    return _enabled


def enable(port=None, rank=None, host="127.0.0.1"):
    """Start the per-rank introspection server. `port` defaults to the
    `scope_port` knob; 0 binds an ephemeral port (tests). Idempotent —
    a second enable() with the server already up is a no-op. Returns the
    bound port."""
    global _enabled, _state, _server
    with _lock:
        if _server is not None:
            _enabled = True
            return _server.port
        fresh = _state is None
        if fresh:
            _state = ScopeState(rank=rank)
        elif rank is not None:
            _state.rank_override = int(rank)
        p = int(port if port is not None else _config.get("scope_port"))
        try:
            _server = ScopeServer(_state, port=p, host=host)
        except OSError:
            if fresh:
                _state = None   # failed arm keeps the zero-alloc path
            raise
        _enabled = True
    print(f"mx.scope: rank {_state.rank()} introspection server on "
          f"{_server.url} (/healthz /metrics /statusz /tracez /profilez)",
          file=sys.stderr)
    return _server.port


def maybe_enable():
    """Arm iff the `scope` knob asks (called at trainer construction,
    like guard/memsafe — a config read at construction time only; the
    step hot path keeps its single module-bool check). A taken port
    warns instead of raising: knob-driven introspection must never kill
    the training run it observes (an explicit enable() still raises)."""
    if _enabled:
        return True
    if _config.get("scope") == "on":
        try:
            enable()
        except OSError as e:
            print(f"mx.scope: cannot bind port "
                  f"{_config.get('scope_port')}: {e} — introspection "
                  "disabled for this run", file=sys.stderr)
    return _enabled


def disable():
    """Stop the server and release the state: back to the zero-thread,
    zero-allocation fast path. A live device capture is stopped so the
    jax.profiler session is never left dangling."""
    global _enabled, _state, _server
    with _lock:
        _enabled = False
        srv, _server = _server, None
        st, _state = _state, None
    if st is not None:
        st.abort_profile()
    if srv is not None:
        srv.stop()


def reset():
    """Tests/run boundaries: same as disable() (scope keeps no
    cross-run state beyond the server + step window)."""
    disable()


def port():
    """The bound server port (None while disabled)."""
    return _server.port if _server is not None else None


def url():
    """The server base URL (None while disabled)."""
    return _server.url if _server is not None else None


def on_step(trainer, step):
    """Post-step trainer hook (behind the module bool — never reached
    while disabled; ci sanity counts the calls): records the completed
    step for /healthz + /statusz and drives an armed /profilez capture
    at the step boundary, on the trainer thread."""
    st = _state
    if st is not None:
        st.note_step(trainer, step)


if _config.get("scope") == "on":
    maybe_enable()
