"""Misc utilities (reference: python/mxnet/util.py subset that makes
sense off-GPU; numpy-mode toggles are the 2.x line and out of scope for
this 1.x-surface build — `is_np_array` reports False so shared scripts
can branch)."""
from __future__ import annotations

import functools
import os
import time

__all__ = ["makedirs", "is_np_array", "use_np", "getenv", "setenv",
           "fmt_bytes", "now_us", "perf_to_us", "epoch_unix_ns"]

# The process-wide monotonic trace epoch: ONE (perf_counter, wall-clock)
# anchor pair, captured together at first import, shared by mx.profiler's
# chrome-trace events, mx.telemetry's event mirror, and mx.trace's span
# records — so a merged timeline never mixes clocks with different zero
# points. epoch_unix_ns() maps the monotonic zero back to wall time, which
# is how tools/trace_report.py aligns per-rank span files onto one axis.
_EPOCH_PC_NS = time.perf_counter_ns()
_EPOCH_UNIX_NS = time.time_ns()


def now_us():
    """Microseconds since the shared monotonic trace epoch."""
    return (time.perf_counter_ns() - _EPOCH_PC_NS) / 1e3


def perf_to_us(t):
    """Map a raw time.perf_counter() reading (seconds) onto the shared
    microsecond epoch, so timestamps captured before a record call lands
    on the same axis as now_us()."""
    return t * 1e6 - _EPOCH_PC_NS / 1e3


def epoch_unix_ns():
    """Wall-clock time (ns since the unix epoch) at the monotonic epoch's
    zero point: absolute_ns = epoch_unix_ns() + round(ts_us * 1000)."""
    return _EPOCH_UNIX_NS


def fmt_bytes(n, show_raw=False):
    """Human-readable byte count: '1.50 GiB', or with show_raw
    '1.50 GiB (1610612736 bytes)' — shared by mx.memsafe error messages
    and mx.check findings so the two subsystems format identically."""
    n = int(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            human = f"{n / div:.2f} {unit}"
            return f"{human} ({n} bytes)" if show_raw else human
    return f"{n} bytes" if show_raw else f"{n} B"


def makedirs(d):
    """mkdir -p (the reference kept this for py2 compat; harmless)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def is_np_array():
    """Numpy-semantics mode is the MXNet 2.x line — always False here."""
    return False


def use_np(func):
    """2.x numpy-mode decorator: accepted and returned unchanged (ops
    here already follow numpy-style broadcasting)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapper


def getenv(name):
    """Reference MXGetEnv facade."""
    return os.environ.get(name)


def setenv(name, value):
    """Reference MXSetEnv facade."""
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)
