"""Misc utilities (reference: python/mxnet/util.py subset that makes
sense off-GPU; numpy-mode toggles are the 2.x line and out of scope for
this 1.x-surface build — `is_np_array` reports False so shared scripts
can branch)."""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "is_np_array", "use_np", "getenv", "setenv",
           "fmt_bytes"]


def fmt_bytes(n, show_raw=False):
    """Human-readable byte count: '1.50 GiB', or with show_raw
    '1.50 GiB (1610612736 bytes)' — shared by mx.memsafe error messages
    and mx.check findings so the two subsystems format identically."""
    n = int(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            human = f"{n / div:.2f} {unit}"
            return f"{human} ({n} bytes)" if show_raw else human
    return f"{n} bytes" if show_raw else f"{n} B"


def makedirs(d):
    """mkdir -p (the reference kept this for py2 compat; harmless)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def is_np_array():
    """Numpy-semantics mode is the MXNet 2.x line — always False here."""
    return False


def use_np(func):
    """2.x numpy-mode decorator: accepted and returned unchanged (ops
    here already follow numpy-style broadcasting)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapper


def getenv(name):
    """Reference MXGetEnv facade."""
    return os.environ.get(name)


def setenv(name, value):
    """Reference MXSetEnv facade."""
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)
