"""mx.dataflow — the input-to-device performance layer.

The reference framework hid host-side input work behind device compute with
an async `PrefetcherIter` (`src/io/iter_prefetcher.h`, SURVEY §2.1): a
background thread stages the *next* batches while the current one trains.
The TPU-native equivalent staged here is stronger — batches are not just
decoded ahead of time, they are already mesh-sharded `jax.Array`s by the
time the train step sees them, so the H2D transfer itself overlaps device
compute instead of serializing with it:

  * `prefetch_to_mesh(it, trainer, depth=2)` — background thread converts
    host batches (numpy / NDArray trees) into sharded device arrays for the
    next `depth` steps using the trainer's own batch shardings (including
    data_specs/label_specs overrides); worker exceptions surface at
    `next()` with their original traceback; the thread shuts down cleanly
    on close()/GC/partial iteration.
  * `BucketPad(axis_buckets=...)` — pads varlen batches up to configured or
    power-of-two buckets (pairing each pad with a valid-length input) so a
    stream of novel sequence lengths compiles a handful of executables
    instead of one per length.
  * `ensure_compile_cache()` — wires jax's persistent XLA compilation cache
    from the `compile_cache_dir` knob at first trainer construction, so
    relaunches skip cold compiles entirely.

Telemetry (all series degrade to a module-bool check when disabled):
`dataloader_prefetch_depth{stage="device"}` (staged-batch depth, distinct
from the host DataLoader's series so input-stall attribution can name the
bottleneck stage), `device_prefetch_wait_seconds` (consumer blocked on
staging), `h2d_bytes_total` (payload staged onto the mesh), and
`bucket_pad_waste_ratio` (padding overhead next to the recompiles it
eliminates).
"""
from __future__ import annotations

import math
import os
import queue
import sys
import threading
import time

import numpy as np

from . import _locklint
from . import config as _config
from . import goodput as _goodput
from . import guard as _guard
from . import resilience as _resilience
from . import telemetry as _telemetry
from . import trace as _trace

__all__ = ["prefetch_to_mesh", "MeshPrefetcher", "BucketPad",
           "bucket_length", "ensure_compile_cache", "autofit",
           "AutofitResult"]

_M_DEPTH = _telemetry.gauge(
    "dataloader_prefetch_depth", "batches buffered ahead of the consumer "
    "(0 while the consumer is starved = input-bound); fanned out by stage: "
    "host (DataLoader worker batches) vs device (mesh-staged arrays)")
_M_STAGE_WAIT = _telemetry.histogram(
    "device_prefetch_wait_seconds", "time the training loop spent blocked "
    "waiting for a mesh-staged batch — the H2D-staging share of the input "
    "stall (compare dataloader_wait_seconds for the host-batch share)")
_M_H2D_BYTES = _telemetry.counter(
    "h2d_bytes_total", "payload bytes staged host-to-device by "
    "prefetch_to_mesh")
_M_PAD_WASTE = _telemetry.histogram(
    "bucket_pad_waste_ratio", "fraction of each BucketPad-padded batch "
    "that is padding (0 = exact bucket fit) — the overhead bought to "
    "bound the jit-cache population",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.2, 0.3, 0.5, 0.75, 1.0))
_M_CACHE_HITS = _telemetry.counter(
    "compile_cache_hits_total", "compiles served from the persistent XLA "
    "compilation cache (warm: deserialized, not rebuilt)")
_M_CACHE_MISSES = _telemetry.counter(
    "compile_cache_misses_total", "compiles the persistent cache could not "
    "serve (cold: full XLA compile, then written back)")


# ---------------------------------------------------------------------------
# tree helpers (nested tuple/list/dict/namedtuple batches of NDArray /
# numpy / jax arrays — jax.tree_util preserves the node types exactly, and
# NDArray, being unregistered, is a leaf)
# ---------------------------------------------------------------------------

def _raw(leaf):
    """Strip an NDArray wrapper down to its jax/numpy payload."""
    from .ndarray import NDArray
    if isinstance(leaf, NDArray):
        return leaf._data
    return leaf


# ---------------------------------------------------------------------------
# prefetch_to_mesh
# ---------------------------------------------------------------------------

class _WorkerExit(Exception):
    """Internal: the prefetcher was closed under the worker."""


_STOP = object()


class MeshPrefetcher:
    """Background-staged iterator: host batches in, mesh-sharded device
    batches out, `depth` steps ahead of the consumer.

    `shardings` may be a ShardedTrainer (its `_batch_shardings` — including
    data_specs/label_specs overrides — decide placement; batches must then
    be `(data, labels)` pairs), an explicit list of `jax.sharding.Sharding`
    per leaf, or None (plain committed default-device placement — the eager
    gluon/Estimator path). `transform` (e.g. a BucketPad) runs inside the
    worker thread so host-side padding overlaps device compute too."""

    def __init__(self, iterator, shardings=None, depth=2, transform=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q = queue.Queue(maxsize=depth)
        self._closed = threading.Event()
        self._exhausted = False
        # close() is idempotent and may be called concurrently — including
        # from a SIGTERM/preemption path re-entering while the first close
        # is mid-join — so its bookkeeping sits behind an RLock
        self._close_lock = _locklint.make_rlock("dataflow.prefetcher.close")
        self._close_done = False
        # the worker closes over locals (not self) so a consumer dropping
        # its last reference lets __del__ run while the thread is alive
        closed, q = self._closed, self._q
        stage = _Stager(shardings)
        source = iter(iterator)
        policy_cell = [None]   # RetryPolicy built once, on first enabled use

        def _worker():
            try:
                for item in source:
                    if closed.is_set():
                        return
                    if transform is not None:
                        item = transform(item)
                    staged = _stage_resilient(stage, item, closed,
                                              policy_cell)
                    _q_put(q, staged, closed)
                _q_put(q, _STOP, closed)
            except _WorkerExit:
                return
            except BaseException as e:   # noqa: BLE001 — relayed to consumer
                try:
                    _q_put(q, e, closed)
                except _WorkerExit:
                    return

        self._thread = threading.Thread(
            target=_worker, name="mx-dataflow-prefetch", daemon=True)
        self._thread.start()

    # -- consumer side --------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted or self._closed.is_set():
            raise StopIteration
        if _telemetry._enabled or _trace._enabled or _goodput._enabled:
            t0 = time.perf_counter()
            item = self._q.get()
            if item is not _STOP and not isinstance(item, BaseException):
                # waits that produced a batch are the H2D-staging stall;
                # waiting for the end-of-stream marker is not a stall
                t1 = time.perf_counter()
                if _telemetry._enabled:
                    _M_STAGE_WAIT.observe(t1 - t0)
                    _M_DEPTH.labels(stage="device").set(self._q.qsize())
                if _trace._enabled:
                    # the consumer-visible input stall: how long the train
                    # loop sat blocked waiting for a mesh-staged batch —
                    # the span trace_report's input-bound verdict sums
                    _trace.record_span("input.batch_wait", t0, t1,
                                       cat="input")
                if _goodput._enabled:
                    # the same consumer-visible wait, accounted as
                    # badput:input_stall wall-clock
                    _goodput.note("input_stall", t0, t1)
        else:
            item = self._q.get()
        if item is _STOP:
            self._exhausted = True
            self._thread.join()
            raise StopIteration
        if isinstance(item, BaseException):
            self._exhausted = True
            self._thread.join()
            # re-raise the worker's exception object: its __traceback__
            # still points at the failing frame inside the worker
            raise item
        return item

    def close(self):
        """Stop the worker and release the staged batches. Idempotent and
        thread-safe — callable again from a SIGTERM/preemption path while
        a worker is mid-`device_put` (the in-flight transfer completes,
        its result is drained, the worker exits at the next bounded put).
        Called by __del__ and __exit__, safe mid-iteration. A worker
        blocked INSIDE the source iterator's next() cannot be interrupted
        (no thread cancellation in Python) — it is abandoned as a daemon
        and exits at the source's next yield; the join timeout bounds how
        long close() waits for that."""
        with self._close_lock:
            if self._close_done:
                return
            self._closed.set()
            # drain so a worker blocked on put() observes the close promptly
            self._drain()
            if self._thread is not threading.current_thread():
                self._thread.join(timeout=5)
            # a put already in flight during the first drain can land in the
            # emptied queue; drain again after the join so close() really
            # does release every staged device batch
            self._drain()
            # only a confirmed-dead worker makes close() a no-op next time:
            # a timed-out join leaves it retryable
            if not self._thread.is_alive():
                self._close_done = True

    def _drain(self):
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _stage_resilient(stage, item, closed, policy_cell):
    """One batch through the stager. With mx.resilience enabled, the
    `stall_input` fault point fires here and transient staging failures
    (OSError/ConnectionError/TimeoutError — e.g. a flaky remote
    filesystem feeding device_put) retry under the configured
    RetryPolicy. The policy is built ONCE per prefetcher (policy_cell) —
    not per batch, this is the input hot path — and retries abort early
    if the prefetcher closes underneath. Disabled: one bool check, then
    the plain call."""
    if _guard._enabled:
        # mx.guard liveness from the input worker: a trainer blocked on
        # a slow input queue still shows a fresh beat (phase=input), so
        # the supervisor distinguishes "starving" from "dead" — the
        # in-memory record updates every batch, the file write stays
        # rate-limited
        _guard.heartbeat(phase="input")
    if not _resilience._enabled:
        return stage(item)
    _resilience.fault_point("input")
    if policy_cell[0] is None:
        policy_cell[0] = _resilience.RetryPolicy()
    return policy_cell[0].call(
        stage, item, site="prefetch-stage", abort=closed.is_set)


def _q_put(q, item, closed):
    """Bounded put that aborts when the prefetcher closes underneath the
    worker (the consumer stopped iterating; blocking forever would leak
    the thread)."""
    while not closed.is_set():
        try:
            q.put(item, timeout=0.05)
            return
        except queue.Full:
            continue
    raise _WorkerExit


class _Stager:
    """Per-batch host->mesh staging: flatten the batch tree, device_put
    every leaf with its target sharding (one batched transfer), rebuild
    the tree as NDArrays."""

    def __init__(self, shardings):
        self._shardings = shardings

    def __call__(self, item):
        import jax

        from .ndarray import NDArray

        t_trace = time.perf_counter() if _trace._enabled else None
        leaves, treedef = jax.tree_util.tree_flatten(
            item, is_leaf=lambda x: isinstance(x, NDArray))
        raw = [_raw(x) for x in leaves]
        targets = self._targets(item, raw)
        if _telemetry._enabled:
            moved = 0
            for r, s in zip(raw, targets or [None] * len(raw)):
                if isinstance(r, np.ndarray):
                    moved += r.nbytes
                elif s is not None and getattr(r, "sharding", None) != s:
                    moved += getattr(r, "nbytes", 0)
            if moved:
                _M_H2D_BYTES.inc(moved)
        if targets is None:
            staged = [jax.device_put(r) for r in raw]
        else:
            staged = [r if getattr(r, "sharding", None) == t
                      else jax.device_put(r, t)
                      for r, t in zip(raw, targets)]
        out = jax.tree_util.tree_unflatten(
            treedef, [NDArray(s) for s in staged])
        if t_trace is not None:
            # producer-side H2D staging (runs in the prefetch worker
            # thread, overlapped with device compute — a long span here
            # that never surfaces as batch_wait means the overlap worked)
            _trace.record_span("input.h2d_stage", t_trace, cat="input")
        return out

    def _targets(self, item, raw):
        sh = self._shardings
        if sh is None:
            return None
        if isinstance(sh, (list, tuple)):
            if len(sh) != len(raw):
                raise ValueError(
                    f"got {len(sh)} shardings for a batch of {len(raw)} "
                    "arrays")
            return list(sh)
        # a ShardedTrainer (or anything exposing _batch_shardings): batches
        # are (data, labels) pairs; count leaves on each side
        if hasattr(sh, "_batch_shardings"):
            if not (isinstance(item, (tuple, list)) and len(item) == 2):
                n_data, n_label = len(raw), 0
            else:
                import jax

                from .ndarray import NDArray
                n_data = len(jax.tree_util.tree_leaves(
                    item[0], is_leaf=lambda x: isinstance(x, NDArray)))
                n_label = len(raw) - n_data
            shapes = tuple(tuple(getattr(r, "shape", ())) for r in raw)
            return list(sh._batch_shardings(n_data, n_label, shapes))
        raise TypeError(
            "shardings must be None, a list of jax shardings, or a trainer "
            f"with _batch_shardings; got {type(sh).__name__}")


def prefetch_to_mesh(iterator, trainer_or_shardings=None, depth=None,
                     transform=None):
    """Stage batches onto the mesh `depth` steps ahead of the consumer.

    Wrap any host batch iterator (a gluon DataLoader, a generator of
    `(data, labels)` pairs) and iterate the result instead: a background
    thread converts each batch into mesh-sharded device arrays while the
    current step runs, so H2D transfer overlaps compute. Pass the
    ShardedTrainer to reuse its batch shardings (data_specs/label_specs
    included), an explicit sharding list, or None for default-device
    placement (the eager gluon path). `transform` (e.g. `BucketPad`) runs
    in the worker thread. Close via `close()`, a `with` block, or just
    dropping the iterator; worker exceptions re-raise at `next()` with
    their original traceback."""
    if depth is None:
        depth = _config.get("device_prefetch_depth") or 2
    return MeshPrefetcher(iterator, trainer_or_shardings, depth=depth,
                          transform=transform)


# ---------------------------------------------------------------------------
# shape bucketing
# ---------------------------------------------------------------------------

def bucket_length(length, buckets="pow2", floor=None):
    """The bucket a raw length rounds up to — the ONE bucketing policy
    shared by `BucketPad` (varlen batch axes) and `mx.serve` (KV-cache
    lengths), so the two subsystems can never bucket the same stream
    differently. `buckets` is a sorted sequence of sizes or \"pow2\"
    (next power of two, floored at `floor` — default the
    `bucket_pad_min` knob). Lengths above the largest configured bucket
    keep their raw size (one compile per such outlier, same as
    unbucketed)."""
    length = int(length)
    if buckets == "pow2":
        if floor is None:
            floor = max(1, int(_config.get("bucket_pad_min")))
        return max(int(floor),
                   1 << max(0, math.ceil(math.log2(max(length, 1)))))
    for b in buckets:
        if b >= length:
            return int(b)
    return length


class BucketPad:
    """Pad varlen batches up to configured (or power-of-two) buckets so a
    stream of novel raw lengths compiles a bounded set of step executables.

    axis_buckets: {axis: buckets} where buckets is a sorted sequence of
    sizes or the string "pow2" (next power of two, floored at the
    `bucket_pad_min` knob). Default: {1: "pow2"} — the sequence axis.
    Lengths above the largest configured bucket keep their raw size (a
    compile per such outlier, same as unbucketed).

    Each padded DATA array is paired with a valid-length input (int32,
    shape (batch,), the raw length) appended to the data list, so masked
    models/losses can ignore the pad; pass append_valid_length=False for
    workloads (e.g. BERT) whose batch already carries one. Labels are
    padded along the same axes with `label_pad_value` but never grow a
    valid-length input.

    Use per batch (`bp((data, labels))`), over an iterator (`bp.wrap(it)`),
    or as `prefetch_to_mesh(..., transform=bp)` — there the padding happens
    in the prefetch worker thread and overlaps device compute."""

    def __init__(self, axis_buckets=None, pad_value=0, label_pad_value=0,
                 append_valid_length=True):
        self.axis_buckets = dict(axis_buckets) if axis_buckets else {1: "pow2"}
        for axis, buckets in self.axis_buckets.items():
            if buckets != "pow2":
                bl = sorted(int(b) for b in buckets)
                if not bl:
                    raise ValueError(f"axis {axis}: empty bucket list")
                self.axis_buckets[axis] = bl
        self.pad_value = pad_value
        self.label_pad_value = label_pad_value
        self.append_valid_length = append_valid_length

    def _bucket(self, length, buckets):
        return bucket_length(length, buckets)

    def _pad_leaf(self, leaf, pad_value, collect_valid):
        arr = _raw(leaf)
        padded = arr
        raw_elems = int(np.prod(arr.shape)) if arr.ndim else 1
        valid = None
        pads = [(0, 0)] * arr.ndim
        grew = False
        for axis, buckets in self.axis_buckets.items():
            if axis >= arr.ndim:
                continue
            length = arr.shape[axis]
            target = self._bucket(length, buckets)
            if target > length:
                pads[axis] = (0, target - length)
                grew = True
            if collect_valid and valid is None:
                valid = np.full(arr.shape[0] if arr.ndim else 1, length,
                                dtype=np.int32)
        if grew:
            host = np.asarray(arr)
            padded = np.pad(host, pads, constant_values=pad_value)
            if _telemetry._enabled:
                _M_PAD_WASTE.observe(
                    1.0 - raw_elems / max(int(np.prod(padded.shape)), 1))
        elif _telemetry._enabled and any(
                ax < arr.ndim for ax in self.axis_buckets):
            _M_PAD_WASTE.observe(0.0)
        return padded, (valid if grew or collect_valid else None)

    def _pad_side(self, side, pad_value, collect_valid):
        single = not isinstance(side, (list, tuple))
        items = [side] if single else list(side)
        out, valids = [], []
        for leaf in items:
            padded, valid = self._pad_leaf(leaf, pad_value, collect_valid)
            out.append(padded)
            if valid is not None:
                valids.append(valid)
        if collect_valid:
            out.extend(valids)
            return out
        return out[0] if single else out

    def __call__(self, batch):
        """One batch: a `(data, labels)` pair, or a bare data array/list."""
        if isinstance(batch, tuple) and len(batch) == 2 and any(
                isinstance(s, (list, tuple)) or hasattr(_raw(s), "ndim")
                for s in batch):
            data, labels = batch
            data = self._pad_side(data, self.pad_value,
                                  self.append_valid_length)
            labels = self._pad_side(labels, self.label_pad_value, False)
            return (data, labels)
        return self._pad_side(batch, self.pad_value, self.append_valid_length)

    def wrap(self, iterator):
        """Generator applying the pad to every batch of `iterator`."""
        for batch in iterator:
            yield self(batch)


# ---------------------------------------------------------------------------
# auto-fit: the largest batch/bucket configuration that fits the device
# ---------------------------------------------------------------------------


class AutofitResult:
    """What `autofit` chose and how it got there.

    Fields: `batch_size` (largest fitting global batch), `predicted_bytes`
    / `exec_peak_bytes` / `resident_bytes` (the chosen config's plan),
    `capacity_bytes`, `headroom_bytes`, `buckets` (the BucketPad
    boundaries that fit at the chosen batch, when bucket lengths were
    probed), `next_larger` ({"batch_size", "predicted_bytes"} of the
    smallest probed config that did NOT fit — None when the search was
    capped by max_batch), and `probes` (every AOT plan, in probe order).
    `bucket_pad(**kwargs)` builds the matching BucketPad; feed
    `batch_size` straight into the data pipeline and train."""

    def __init__(self, batch_size, plan, capacity_bytes, probes,
                 buckets=None, next_larger=None):
        self.batch_size = batch_size
        self.predicted_bytes = plan["predicted_bytes"]
        self.exec_peak_bytes = plan["exec_peak_bytes"]
        self.resident_bytes = plan["resident_bytes"]
        self.capacity_bytes = capacity_bytes
        self.headroom_bytes = capacity_bytes - plan["predicted_bytes"]
        self.buckets = list(buckets) if buckets is not None else None
        self.next_larger = next_larger
        self.probes = list(probes)

    def bucket_pad(self, axis=1, **kwargs):
        """A BucketPad over the bucket boundaries that fit (only when
        autofit probed buckets)."""
        if not self.buckets:
            raise ValueError("autofit ran without bucket candidates — "
                             "pass buckets=[...] to probe them")
        return BucketPad(axis_buckets={axis: list(self.buckets)}, **kwargs)

    def as_dict(self):
        return {
            "batch_size": self.batch_size,
            "predicted_bytes": self.predicted_bytes,
            "exec_peak_bytes": self.exec_peak_bytes,
            "resident_bytes": self.resident_bytes,
            "capacity_bytes": self.capacity_bytes,
            "headroom_bytes": self.headroom_bytes,
            "buckets": self.buckets,
            "next_larger": self.next_larger,
            "probes": self.probes,
        }

    def __repr__(self):
        extra = f", buckets={self.buckets}" if self.buckets else ""
        return (f"AutofitResult(batch_size={self.batch_size}, "
                f"predicted={self.predicted_bytes}, "
                f"capacity={self.capacity_bytes}{extra})")


def autofit(trainer, make_batch, max_batch=1024, capacity=None,
            buckets=None, multiple_of=None, verbose=True):
    """Binary-search the largest batch size (and optionally the BucketPad
    bucket boundaries) whose PREDICTED train-step peak fits the device —
    AOT lowering + XLA memory_analysis only, no device step executes and
    no batch transfers (mx.memsafe, "Memory Safe Computations with XLA").

    `make_batch(batch_size)` (or `make_batch(batch_size, seq_len)` when
    `buckets` is given) returns one `(data, labels)` host batch — numpy /
    NDArray; only shapes and dtypes are read. Candidates are multiples of
    `multiple_of` (default: the mesh's data-axis extent, so every probe
    shards evenly). `capacity` defaults to mx.memsafe.capacity_bytes()
    (the `device_bytes_limit` knob, else device memory_stats). When
    `buckets` (sequence lengths) is given, the batch search runs at the
    LARGEST bucket and each bucket is then verified at the chosen batch —
    the result's `.bucket_pad()` keeps exactly the fitting boundaries.

    Returns an AutofitResult; raises MemoryBudgetError when even the
    smallest candidate does not fit (carrying that candidate's plan)."""
    from . import memsafe as _memsafe

    cap = capacity if capacity is not None else _memsafe.capacity_bytes()
    if not cap:
        raise ValueError(
            "autofit needs a device capacity: set the device_bytes_limit "
            "knob (simulated capacity), pass capacity=, or run on a "
            "backend whose device.memory_stats() reports bytes_limit")
    cap = int(cap)
    m = int(multiple_of) if multiple_of else _data_axis_extent(trainer)
    k_max = max(1, int(max_batch) // m)
    probes = []

    def plan(batch_size, seq_len=None):
        batch = make_batch(batch_size) if seq_len is None \
            else make_batch(batch_size, seq_len)
        data, labels = batch
        info = trainer.predict_step_bytes(data, labels)
        # capacity/headroom/fits re-derived against THE SEARCH capacity
        # (the caller's capacity= may differ from the memsafe-global one
        # predict_step_bytes consulted) so every probe record is
        # internally consistent
        info = dict(info, batch_size=batch_size, seq_len=seq_len,
                    capacity_bytes=cap,
                    headroom_bytes=cap - info["predicted_bytes"],
                    fits=info["predicted_bytes"] <= cap)
        probes.append(info)
        if verbose:
            print(f"mx.dataflow.autofit: batch {batch_size}"
                  + (f" seq {seq_len}" if seq_len is not None else "")
                  + f" -> predicted {info['predicted_bytes']} bytes "
                  f"({'fits' if info['fits'] else 'over'} capacity {cap})",
                  file=sys.stderr)
        return info

    # anchor the batch search at the LARGEST bucket that fits at the
    # minimum batch; buckets too big for even that are dropped (logged),
    # not fatal — only when NOTHING fits does autofit raise
    dropped = []
    top_seq = None
    lo_info = None
    for cand in (sorted((int(b) for b in buckets), reverse=True)
                 if buckets else [None]):
        lo_info = plan(m, cand)
        if lo_info["fits"]:
            top_seq = cand
            break
        dropped.append(cand)
    if lo_info is None or not lo_info["fits"]:
        raise _memsafe.MemoryBudgetError(
            f"autofit(batch={m})", lo_info["predicted_bytes"], cap,
            exec_peak_bytes=lo_info["exec_peak_bytes"],
            resident_bytes=lo_info["resident_bytes"])
    if dropped and verbose:
        print(f"mx.dataflow.autofit: bucket(s) {sorted(dropped)} exceed "
              f"capacity even at batch {m} — dropped", file=sys.stderr)
    # largest fitting k in [1, k_max]: invariant fits(lo), not fits(hi)
    lo, hi = 1, None
    best = lo_info
    next_larger = None
    if k_max > 1:
        hi_info = plan(k_max * m, top_seq)
        if hi_info["fits"]:
            lo, best = k_max, hi_info
        else:
            hi = k_max
            next_larger = hi_info
            while hi - lo > 1:
                mid = (lo + hi) // 2
                info = plan(mid * m, top_seq)
                if info["fits"]:
                    lo, best = mid, info
                else:
                    hi, next_larger = mid, info
    chosen = lo * m
    fitting_buckets = None
    if buckets:
        fitting_buckets = []
        for L in sorted(int(b) for b in buckets):
            if L in dropped:
                continue
            if L == top_seq:
                # already planned: the batch search ran at this bucket
                fitting_buckets.append(L)
                continue
            if plan(chosen, L)["fits"]:
                fitting_buckets.append(L)
    nl = None
    if next_larger is not None:
        nl = {"batch_size": next_larger["batch_size"],
              "predicted_bytes": next_larger["predicted_bytes"]}
    result = AutofitResult(chosen, best, cap, probes,
                           buckets=fitting_buckets, next_larger=nl)
    if verbose:
        print(f"mx.dataflow.autofit: chose batch {chosen} "
              f"(predicted {result.predicted_bytes} of {cap} bytes, "
              f"headroom {result.headroom_bytes})"
              + (f", buckets {fitting_buckets}" if buckets else "")
              + (f"; batch {nl['batch_size']} would NOT fit "
                 f"({nl['predicted_bytes']} bytes)" if nl else
                 "; search capped at max_batch"),
              file=sys.stderr)
    return result


def _data_axis_extent(trainer):
    """Devices the batch axis shards over (dp*fsdp), so autofit probes
    only evenly-sharding batch sizes; 1 when the trainer has no mesh."""
    mesh = getattr(trainer, "mesh", None)
    if mesh is None:
        return 1
    try:
        return int(mesh.shape.get("dp", 1)) * int(mesh.shape.get("fsdp", 1))
    except Exception:
        return 1


# ---------------------------------------------------------------------------
# persistent XLA compilation cache
# ---------------------------------------------------------------------------

# None = not attempted yet (knob may still be set later); "" = attempted
# and failed (don't retry, don't claim success); path = wired
_cache_state = None
_cache_lock = _locklint.make_lock("dataflow.compile_cache")


def ensure_compile_cache():
    """Wire jax's persistent compilation cache from the `compile_cache_dir`
    knob (idempotent; called at first trainer construction). Relaunches
    then deserialize executables instead of recompiling — the BERT-large
    cold-compile killer. No-op when the knob is empty or the backend
    cannot serialize executables. Returns the wired cache dir, or None
    when the knob is empty or wiring failed."""
    global _cache_state
    with _cache_lock:
        if _cache_state is not None:
            return _cache_state or None
        cache_dir = _config.get("compile_cache_dir")
        if not cache_dir:
            return None          # knob empty: stays re-armable
        try:
            import jax
            cache_dir = os.path.abspath(cache_dir)
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.1)
            _register_cache_listener()
            _cache_state = cache_dir
            return cache_dir
        except Exception as e:  # pragma: no cover - backend-dependent
            _cache_state = ""    # don't retry, and never report success
            import warnings
            warnings.warn(f"persistent compile cache unavailable: {e}")
            return None


_listener_registered = False


def _register_cache_listener():
    """Mirror jax's compilation-cache hit/miss monitoring events into the
    telemetry counters, so reports can separate warm (deserialized) from
    cold (full XLA) compiles."""
    global _listener_registered
    if _listener_registered:
        return
    try:
        from jax import monitoring

        def _on_event(event, **kwargs):
            if event == "/jax/compilation_cache/cache_hits":
                _M_CACHE_HITS.inc()
            elif event == "/jax/compilation_cache/cache_misses":
                _M_CACHE_MISSES.inc()

        monitoring.register_event_listener(_on_event)
        _listener_registered = True
    except Exception:  # pragma: no cover - older jax without monitoring
        pass
