"""Optimizer zoo.

Reference: `python/mxnet/optimizer/optimizer.py` — registry, per-param state,
lr/wd multipliers, multi-precision — over the update kernels in
`src/operator/optimizer_op.cc`. Here the kernels are the pure jax fns in
`mxnet_tpu.ops.optimizer_ops`; XLA fuses each update into one elementwise
kernel, and the sharded train path (mxnet_tpu.parallel) runs them sharded
over the data axis (weight-update sharding).
"""
from __future__ import annotations

import math

from ..base import Registry
from ..ndarray import NDArray, zeros
from ..ndarray import ndarray as _nd
from .. import ops as _ops

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "AdaGrad", "RMSProp",
           "Ftrl", "Signum", "SignSGD", "LAMB", "LARS", "Adamax", "Nadam",
           "AdaDelta", "DCASGD", "SGLD", "FTML", "create", "register"]

_registry = Registry("optimizer")
register = _registry.register


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _registry.get(name)(**kwargs)


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- bookkeeping ----------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        self.lr = lr

    @property
    def learning_rate(self):
        return self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    # -- per-optimizer --------------------------------------------------
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def _clip(self):
        return self.clip_gradient if self.clip_gradient else -1.0


def _assign(weight, new_data):
    weight._data = new_data._data if isinstance(new_data, NDArray) else new_data


def _is_row_sparse(grad):
    from ..ndarray.sparse import RowSparseNDArray
    return isinstance(grad, RowSparseNDArray)


def _sparse_rows(grad, clip, rescale):
    """Prepare (rows, row_grads) for a lazy row-wise update (reference:
    sgd_update/adam_update kRowSparseStorage kernels with lazy_update)."""
    import jax.numpy as jnp
    rows = grad._indices
    g = grad._values * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    return rows, g


@register("sgd")
class SGD(Optimizer):
    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.multi_precision and weight.dtype != "float32":
            w32 = NDArray(weight._data.astype("float32"))
            mom = zeros(weight.shape) if self.momentum else None
            return (mom, w32)
        if self.momentum:
            return zeros(weight.shape, dtype="float32")
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if _is_row_sparse(grad) and not self.lazy_update:
            grad = grad.tostype("default")  # non-lazy: decay ALL rows
        if _is_row_sparse(grad):
            import jax.numpy as jnp
            rows, g = _sparse_rows(grad, self._clip(), self.rescale_grad)
            # multi_precision: do the row math on the fp32 master copy,
            # then mirror the touched rows into the low-precision weight.
            w32 = state[1] if (self.multi_precision
                               and isinstance(state, tuple)) else None
            master = w32._data if w32 is not None else weight._data
            w_rows = master[rows]
            g = g.astype(w_rows.dtype) + wd * w_rows
            mom = state[0] if isinstance(state, tuple) else state
            if self.momentum and mom is not None:
                m_rows = self.momentum * mom._data[rows] - lr * g
                mom._data = mom._data.at[rows].set(m_rows)
                new_rows = w_rows + m_rows
            else:
                new_rows = w_rows - lr * g
            if w32 is not None:
                w32._data = w32._data.at[rows].set(new_rows)
            weight._data = weight._data.at[rows].set(
                new_rows.astype(weight._data.dtype))
            return
        if self.multi_precision and isinstance(state, tuple):
            mom, w32 = state
            if mom is not None:
                new_w, new_mom, new_w32 = _ops.OPS["mp_sgd_mom_update"](
                    weight._data, grad._data, mom._data, w32._data, lr,
                    momentum=self.momentum, wd=wd,
                    rescale_grad=self.rescale_grad, clip_gradient=self._clip())
                mom._data = new_mom
            else:
                new_w, new_w32 = _ops.OPS["mp_sgd_update"](
                    weight._data, grad._data, w32._data, lr, wd=wd,
                    rescale_grad=self.rescale_grad, clip_gradient=self._clip())
            w32._data = new_w32
            weight._data = new_w
        elif self.momentum:
            new_w, new_mom = _ops.OPS["sgd_mom_update"](
                weight._data, grad._data, state._data, lr,
                momentum=self.momentum, wd=wd,
                rescale_grad=self.rescale_grad, clip_gradient=self._clip())
            state._data = new_mom
            weight._data = new_w
        else:
            weight._data = _ops.OPS["sgd_update"](
                weight._data, grad._data, lr, wd=wd,
                rescale_grad=self.rescale_grad, clip_gradient=self._clip())


@register("nag")
class NAG(SGD):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        new_w, new_mom = _ops.OPS["nag_mom_update"](
            weight._data, grad._data, state._data, lr, momentum=self.momentum,
            wd=wd, rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        state._data = new_mom
        weight._data = new_w

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype="float32")


@register("adam")
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"),
                zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        if _is_row_sparse(grad) and not self.lazy_update:
            grad = grad.tostype("default")  # non-lazy: decay ALL moments
        if _is_row_sparse(grad):
            import jax.numpy as jnp
            rows, g = _sparse_rows(grad, self._clip(), self.rescale_grad)
            w_rows = weight._data[rows]
            g = g.astype(jnp.float32) + wd * w_rows.astype(jnp.float32)
            m_rows = self.beta1 * mean._data[rows] + (1 - self.beta1) * g
            v_rows = self.beta2 * var._data[rows] + (1 - self.beta2) * g * g
            mean._data = mean._data.at[rows].set(m_rows)
            var._data = var._data.at[rows].set(v_rows)
            step = -lr * m_rows / (jnp.sqrt(v_rows) + self.epsilon)
            weight._data = weight._data.at[rows].add(
                step.astype(weight._data.dtype))
            return
        new_w, new_mean, new_var = _ops.OPS["adam_update"](
            weight._data, grad._data, mean._data, var._data, lr,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        mean._data, var._data = new_mean, new_var
        weight._data = new_w


@register("adamw")
class AdamW(Adam):
    """Decoupled weight decay (reference: contrib adamw_update)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        new_w, new_mean, new_var = _ops.OPS["adamw_update"](
            weight._data, grad._data, mean._data, var._data, lr,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        mean._data, var._data = new_mean, new_var
        weight._data = new_w


@register("adagrad")
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype="float32")

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        new_w, new_hist = _ops.OPS["adagrad_update"](
            weight._data, grad._data, state._data, lr,
            epsilon=self.float_stable_eps, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        state._data = new_hist
        weight._data = new_w


@register("rmsprop")
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights or -1.0

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, dtype="float32"), zeros(weight.shape, dtype="float32"),
                    zeros(weight.shape, dtype="float32"))
        return zeros(weight.shape, dtype="float32")

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self.centered:
            n, g_avg, delta = state
            new_w, nn, ng, nd_ = _ops.OPS["rmspropalex_update"](
                weight._data, grad._data, n._data, g_avg._data, delta._data, lr,
                gamma1=self.gamma1, gamma2=self.gamma2, epsilon=self.epsilon,
                wd=wd, rescale_grad=self.rescale_grad, clip_gradient=self._clip())
            n._data, g_avg._data, delta._data = nn, ng, nd_
        else:
            new_w, nn = _ops.OPS["rmsprop_update"](
                weight._data, grad._data, state._data, lr, gamma1=self.gamma1,
                epsilon=self.epsilon, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self._clip(), clip_weights=self.clip_weights)
            state._data = nn
        weight._data = new_w


@register("ftrl")
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"), zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        new_w, nz, nn = _ops.OPS["ftrl_update"](
            weight._data, grad._data, z._data, n._data, lr, lamda1=self.lamda1,
            beta=self.beta, wd=wd, rescale_grad=self.rescale_grad,
            clip_gradient=self._clip())
        z._data, n._data = nz, nn
        weight._data = new_w


@register("signum")
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype="float32") if self.momentum else None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is not None:
            new_w, new_mom = _ops.OPS["signum_update"](
                weight._data, grad._data, state._data, lr, momentum=self.momentum,
                wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self._clip(), wd_lh=self.wd_lh)
            state._data = new_mom
        else:
            new_w = _ops.OPS["signsgd_update"](
                weight._data, grad._data, lr, wd=wd,
                rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        weight._data = new_w


@register("signsgd")
class SignSGD(Signum):
    def __init__(self, **kwargs):
        super().__init__(momentum=0.0, **kwargs)


@register("lamb")
class LAMB(Optimizer):
    """Layer-wise adaptive moments for large-batch BERT (reference:
    `lamb_update_phase1/2` in `src/operator/optimizer_op.cc`, mxnet 1.6)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound or -1.0
        self.upper_bound = upper_bound or -1.0
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"), zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        mean, var = state
        new_w, new_mean, new_var = _ops.OPS["lamb_update"](
            weight._data, grad._data, mean._data, var._data, lr,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, t=t,
            bias_correction=self.bias_correction, wd=wd,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip(),
            lower_bound=self.lower_bound, upper_bound=self.upper_bound)
        mean._data, var._data = new_mean, new_var
        weight._data = new_w


@register("lars")
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference: 1.6 LARS)."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype="float32")

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w32 = weight._data.astype("float32")
        g = grad._data.astype("float32") * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(w32)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        trust = jnp.where((w_norm > 0) & (g_norm > 0),
                          self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
                          jnp.ones_like(w_norm))
        new_mom = self.momentum * state._data - lr * trust * (g + wd * w32)
        state._data = new_mom
        weight._data = (w32 + new_mom).astype(weight.dtype)


@register("adamax")
class Adamax(Optimizer):
    """Adam with an infinity-norm second moment (reference optimizer of
    the same name)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"),
                zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _dense_grad_f32(grad, self._clip(), self.rescale_grad, wd,
                            weight._data)
        m, u = state
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        u._data = jnp.maximum(self.beta2 * u._data, jnp.abs(g))
        step = (lr / (1 - self.beta1 ** t)) * m._data \
            / (u._data + self.epsilon)
        weight._data = (weight._data.astype(jnp.float32) - step) \
            .astype(weight.dtype)


@register("nadam")
class Nadam(Optimizer):
    """Nesterov Adam with momentum schedule (reference Nadam,
    schedule_decay as in Dozat 2016)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self._m_schedule = {}

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"),
                zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _dense_grad_f32(grad, self._clip(), self.rescale_grad, wd,
                            weight._data)
        mu_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_t1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1)
                                                 * self.schedule_decay))
        sched = self._m_schedule.get(index, 1.0) * mu_t
        self._m_schedule[index] = sched
        sched_next = sched * mu_t1
        m, v = state
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        g_prime = g / (1 - sched)
        m_prime = m._data / (1 - sched_next)
        v_prime = v._data / (1 - self.beta2 ** t)
        m_bar = (1 - mu_t) * g_prime + mu_t1 * m_prime
        step = lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon)
        weight._data = (weight._data.astype(jnp.float32) - step) \
            .astype(weight.dtype)


@register("adadelta")
class AdaDelta(Optimizer):
    """Accumulated-delta adaptive method (reference AdaDelta; no fixed
    learning rate — `rho` and `epsilon` govern the step)."""

    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"),
                zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        wd = self._get_wd(index)
        g = _dense_grad_f32(grad, self._clip(), self.rescale_grad, wd,
                            weight._data)
        acc_g, acc_d = state
        acc_g._data = self.rho * acc_g._data + (1 - self.rho) * g * g
        step = jnp.sqrt(acc_d._data + self.epsilon) \
            / jnp.sqrt(acc_g._data + self.epsilon) * g
        acc_d._data = self.rho * acc_d._data + (1 - self.rho) * step * step
        weight._data = (weight._data.astype(jnp.float32) - step) \
            .astype(weight.dtype)


@register("dcasgd")
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference DCASGD): compensates stale
    gradients with lambda * g^2 * (w - w_prev). On TPU training is
    synchronous, so the compensation term is usually zero — kept for
    script compatibility."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = zeros(weight.shape, dtype="float32") if self.momentum else None
        prev = NDArray(weight._data.astype("float32"))
        return (mom, prev)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w32 = weight._data.astype(jnp.float32)
        g = _dense_grad_f32(grad, self._clip(), self.rescale_grad, wd, w32)
        mom, prev = state
        comp = g + self.lamda * g * g * (w32 - prev._data)
        if mom is not None:
            mom._data = self.momentum * mom._data - lr * comp
            new_w = w32 + mom._data
        else:
            new_w = w32 - lr * comp
        prev._data = new_w
        weight._data = new_w.astype(weight.dtype)


@register("sgld")
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference SGLD): SGD half-step
    plus Gaussian noise scaled by sqrt(lr) — posterior sampling, not just
    optimization."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        import jax
        import jax.numpy as jnp

        from .. import random as _random

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w32 = weight._data.astype(jnp.float32)
        g = _dense_grad_f32(grad, self._clip(), self.rescale_grad, wd, w32)
        noise = jax.random.normal(_random.next_key(), weight.shape,
                                  jnp.float32) * math.sqrt(lr)
        weight._data = (w32 - lr / 2 * g + noise).astype(weight.dtype)


@register("ftml")
class FTML(Optimizer):
    """Follow the Moving Leader (reference FTML, Zheng & Kwok 2017)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype="float32"),   # d
                zeros(weight.shape, dtype="float32"),   # v
                zeros(weight.shape, dtype="float32"))   # z

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _dense_grad_f32(grad, self._clip(), self.rescale_grad, wd,
                            weight._data)
        d, v, z = state
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v._data / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d._data
        z._data = self.beta1 * z._data + (1 - self.beta1) * g \
            - sigma * weight._data.astype(jnp.float32)
        d._data = d_t
        weight._data = (-z._data / d_t).astype(weight.dtype)


def _dense_grad_f32(grad, clip, rescale, wd=0.0, weight=None):
    """Dense f32 gradient with rescale + clip + weight decay applied in
    one place (row_sparse grads are densified — these optimizers have no
    lazy row path). Mirrors ops/optimizer_ops._apply_wd for the
    class-based optimizers."""
    import jax.numpy as jnp
    if _is_row_sparse(grad):
        grad = grad.tostype("default")
    g = grad._data.astype(jnp.float32) * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    if wd and weight is not None:
        g = g + wd * weight.astype(jnp.float32)
    return g
