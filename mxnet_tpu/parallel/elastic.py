"""Preemption-safe training: automatic checkpoint + resume.

The reference has no elastic/failure-recovery subsystem (SURVEY §5.3 —
its answer is manual `Module.save_checkpoint` plus operator discipline).
On TPU this deserves to be first-class: preemptible/spot TPU slices get a
SIGTERM grace window, and multi-host jobs restart from the latest step
rather than from scratch.

`AutoCheckpoint` wraps any trainer exposing `step / save_states /
load_states / num_update` — ShardedTrainer, PipelineTrainer and
SeqPipelineTrainer all do (the pipeline classes via
PipelineCheckpointMixin). Checkpoints include the global RNG stream, so
a resumed run replays the same dropout/shuffle draws:

    ckpt = AutoCheckpoint(trainer, "/ckpts/run1", every_steps=500)
    start = ckpt.restore_latest() or 0          # 0 on a fresh run
    for step in range(start, total_steps):
        loss = ckpt.step(data, labels)          # periodic + preemption save
        if ckpt.preempted:
            break                               # saved; exit cleanly

Design points:
  * saves happen only at STEP BOUNDARIES — a signal handler merely sets a
    flag (async-signal-safe); saving from the signal frame mid-dispatch
    could serialize half-updated device state.
  * checkpoints are step-numbered orbax directories; a `DONE` marker file
    written AFTER `save_states` returns makes partially-written
    checkpoints (killed mid-save) invisible to `restore_latest`.
  * retention keeps the newest `keep` complete checkpoints; deletion runs
    on process 0 only (orbax shards are written per-host, the directory
    layout is shared).

Relation to `mx.resilience` (the full fault-tolerance layer): this class
is the minimal in-loop wrapper; resilience adds atomic verified
checkpoints (manifest + checksums + mesh fingerprint — which these saves
inherit automatically while resilience is enabled, since save_states
routes through the same atomic writer), knob-driven periodic checkpoints
with auto-resume inside ShardedTrainer itself, graceful-preemption exit
codes, supervised relaunch via tools/launch.py --max-restarts, retry
policies, and fault injection. New code should prefer the knobs.

**Elastic resize** (`resize_trainer`): the in-process half of elastic
training. Where the launcher answers worker death by relaunching the
gang at the surviving world size (tools/launch.py --elastic, with the
checkpoint resharded onto the new topology at resume), resize_trainer
redistributes a LIVE ShardedTrainer onto a new mesh without any disk
round-trip: params, optimizer state, aux and the device step counter
move via parallel/reshard.py's planned redistribution (one array at a
time — peak memory bounded by the largest array), the step cache and
collective estimates rebuild for the new topology, and training
continues at the same step with bit-identical state.
"""
from __future__ import annotations

import os
import shutil
import signal
import weakref

import jax

__all__ = ["AutoCheckpoint", "resize_trainer"]


def resize_trainer(trainer, mesh=None, devices=None, **axis_sizes):
    """Redistribute a live ShardedTrainer onto a new mesh, in place.

    Pass an explicit `mesh`, or `devices`/axis sizes forwarded to
    make_mesh (e.g. `resize_trainer(tr, dp=2, devices=jax.devices()[:2])`
    after shrinking, `resize_trainer(tr, dp=-1)` to absorb every device).
    The new mesh becomes the process-current mesh. Parameter mode is
    unchanged — per-parameter shardings are re-derived from it on the new
    mesh (a replicate↔fsdp change rides the checkpoint restore path
    instead, where the canonical per-tensor layout makes it exact).

    Returns the reshard plan actually executed (arrays, bytes, strategy
    counts) — also recorded in reshard telemetry and diagnostics."""
    import jax.numpy as jnp

    from .. import resilience as _resilience
    from . import reshard as _reshard
    from . import specs as _specs
    from .mesh import make_mesh, set_mesh

    if not getattr(trainer, "_ready", False):
        raise RuntimeError(
            "resize_trainer: trainer has deferred-shape parameters — run "
            "one step (or construct on the target mesh) first")
    src_fp = _resilience.trainer_fingerprint(trainer)
    if mesh is None:
        mesh = make_mesh(devices=devices, **axis_sizes)
    else:
        set_mesh(mesh)

    from jax.sharding import NamedSharding

    def _on_new_mesh(s):
        # an explicit Parameter.set_sharding given as a concrete
        # NamedSharding is pinned to the mesh it was built on; carry its
        # SPEC onto the new mesh — otherwise redistribute would see
        # src == dst, no-op, and leave one array on devices the gang no
        # longer owns (PartitionSpec rules already re-derive via
        # param_spec)
        if isinstance(s, NamedSharding) and s.mesh != mesh:
            return NamedSharding(mesh, s.spec)
        return s

    rep = _specs.replicated(mesh)
    pshard = [_on_new_mesh(_specs.param_spec(p, mesh, trainer.param_mode))
              for _, p in trainer._grad_params]
    aux_shard = [_specs.replicated(mesh) for _ in trainer._aux_params]

    # mx.zero: re-plan the optimizer-state sharding for the NEW mesh (a
    # 4-way shard redistributes to a 2-way shard; a shrink to data
    # extent 1 drops back to the unsharded layout)
    from . import zero as _zero
    zero_flat = zero_specs = None
    zero_on = bool(getattr(trainer, "_zero", False))
    if zero_on:
        if trainer._fused:
            zero_flat = _zero.flat_spec(trainer._fl, mesh)
            zero_on = zero_flat is not None
        else:
            zero_specs = _zero.plan_state(trainer.params, pshard,
                                          trainer.opt_state, mesh)
            zero_on = any(s is not None for s in zero_specs)
            if not zero_on:
                zero_specs = None

    sess = _reshard.Session()
    if trainer._fused:
        # the flat f32 master + moments replicate by construction (fused
        # LAMB exists only in replicate mode) — or, zero'd, shard over
        # the new mesh's data axes
        fspec = zero_flat if zero_on else rep
        trainer.params = sess.redistribute(trainer.params, fspec)
        trainer.opt_state = tuple(
            sess.redistribute(z, fspec) for z in trainer.opt_state)
    else:
        trainer.params = [sess.redistribute(a, s)
                          for a, s in zip(trainer.params, pshard)]
        zs_l = zero_specs or [None] * len(pshard)
        trainer.opt_state = [
            tuple(sess.redistribute(z, zs or s) for z in st)
            for st, zs, s in zip(trainer.opt_state, zs_l, pshard)]
    trainer.aux = [sess.redistribute(a, s)
                   for a, s in zip(trainer.aux, aux_shard)]

    trainer.mesh = mesh
    trainer._pshard, trainer._aux_shard, trainer._rep = \
        pshard, aux_shard, rep
    trainer._zero, trainer._zero_specs, trainer._zero_flat = \
        zero_on, zero_specs, zero_flat
    # executables bake the old mesh/shardings in: every cached step is
    # stale. The device counter re-places small enough to skip the session
    trainer._t_dev = jax.device_put(
        jnp.asarray(trainer.num_update, jnp.int32), rep)
    trainer._step_cache.clear()
    trainer._refresh_comm_estimates()
    return sess.finish("resize", src_fp=src_fp,
                       dst_fp=_resilience.trainer_fingerprint(trainer))

_MARKER = "DONE"


class AutoCheckpoint:
    def __init__(self, trainer, directory, every_steps=500, keep=2,
                 on_preemption=True, signals=(signal.SIGTERM,)):
        self.trainer = trainer
        self.directory = str(directory)
        self.every_steps = int(every_steps)
        self.keep = int(keep)
        self._save_pending = False     # cleared once the boundary save runs
        self._preempted = False        # sticky: "a signal arrived"
        self._prev_handlers = {}
        os.makedirs(self.directory, exist_ok=True)
        if on_preemption:
            # the handler holds only a WEAK reference: the process-global
            # signal table must not keep the trainer (the largest object
            # in the program) alive after the AutoCheckpoint is dropped
            ref = weakref.ref(self)

            def _handler(signum, frame, _ref=ref):
                obj = _ref()
                if obj is not None:
                    obj._save_pending = True
                    obj._preempted = True
            for sig in signals:
                try:
                    self._prev_handlers[sig] = signal.signal(sig, _handler)
                except (ValueError, OSError):
                    pass               # non-main thread / restricted env

    @property
    def preempted(self):
        """Sticky: True once a preemption signal has arrived (the boundary
        save does NOT clear it — training loops break on it). Use
        clear_preempted() if the grace window was rescinded."""
        return self._preempted

    def clear_preempted(self):
        self._preempted = False
        self._save_pending = False

    def close(self):
        """Restore previous signal handlers."""
        for sig, h in self._prev_handlers.items():
            try:
                signal.signal(sig, h)
            except (ValueError, OSError):
                pass
        self._prev_handlers = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # --------------------------------------------------------------- steps
    def step(self, *args, **kwargs):
        loss = self.trainer.step(*args, **kwargs)
        n = int(self.trainer.num_update)
        if self._save_pending or (
                self.every_steps > 0 and n % self.every_steps == 0):
            self.save()
            self._save_pending = False  # one boundary save per signal —
            #                             NOT one per subsequent step
        return loss

    # --------------------------------------------------------- checkpoints
    def _step_dir(self, n):
        return os.path.join(self.directory, f"step_{n:010d}")

    def save(self):
        """Checkpoint now (also called automatically by step())."""
        n = int(self.trainer.num_update)
        d = self._step_dir(n)
        self.trainer.save_states(d)
        # marker AFTER a successful save: restore_latest ignores dirs
        # without it, so a kill mid-save can never be resumed from
        if jax.process_index() == 0:
            with open(os.path.join(d, _MARKER), "w") as f:
                f.write(str(n))
        self._retain()
        return d

    def _complete_steps(self):
        out = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for e in entries:
            if e.startswith("step_") and os.path.exists(
                    os.path.join(self.directory, e, _MARKER)):
                try:
                    out.append(int(e[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def _retain(self):
        if jax.process_index() != 0 or self.keep <= 0:
            return
        steps = self._complete_steps()
        for n in steps[:-self.keep]:
            shutil.rmtree(self._step_dir(n), ignore_errors=True)

    def restore_latest(self):
        """Load the newest COMPLETE checkpoint into the trainer. Returns
        its step number, or None when no usable checkpoint exists."""
        steps = self._complete_steps()
        for n in reversed(steps):
            try:
                self.trainer.load_states(self._step_dir(n))
                return n
            except Exception:          # corrupt tail: fall back one
                continue
        return None
