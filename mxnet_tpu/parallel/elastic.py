"""Preemption-safe training: automatic checkpoint + resume.

The reference has no elastic/failure-recovery subsystem (SURVEY §5.3 —
its answer is manual `Module.save_checkpoint` plus operator discipline).
On TPU this deserves to be first-class: preemptible/spot TPU slices get a
SIGTERM grace window, and multi-host jobs restart from the latest step
rather than from scratch.

`AutoCheckpoint` wraps any trainer exposing `step / save_states /
load_states / num_update` — ShardedTrainer, PipelineTrainer and
SeqPipelineTrainer all do (the pipeline classes via
PipelineCheckpointMixin). Checkpoints include the global RNG stream, so
a resumed run replays the same dropout/shuffle draws:

    ckpt = AutoCheckpoint(trainer, "/ckpts/run1", every_steps=500)
    start = ckpt.restore_latest() or 0          # 0 on a fresh run
    for step in range(start, total_steps):
        loss = ckpt.step(data, labels)          # periodic + preemption save
        if ckpt.preempted:
            break                               # saved; exit cleanly

Design points:
  * saves happen only at STEP BOUNDARIES — a signal handler merely sets a
    flag (async-signal-safe); saving from the signal frame mid-dispatch
    could serialize half-updated device state.
  * checkpoints are step-numbered orbax directories; a `DONE` marker file
    written AFTER `save_states` returns makes partially-written
    checkpoints (killed mid-save) invisible to `restore_latest`.
  * retention keeps the newest `keep` complete checkpoints; deletion runs
    on process 0 only (orbax shards are written per-host, the directory
    layout is shared).

Relation to `mx.resilience` (the full fault-tolerance layer): this class
is the minimal in-loop wrapper; resilience adds atomic verified
checkpoints (manifest + checksums + mesh fingerprint — which these saves
inherit automatically while resilience is enabled, since save_states
routes through the same atomic writer), knob-driven periodic checkpoints
with auto-resume inside ShardedTrainer itself, graceful-preemption exit
codes, supervised relaunch via tools/launch.py --max-restarts, retry
policies, and fault injection. New code should prefer the knobs.
"""
from __future__ import annotations

import os
import shutil
import signal
import weakref

import jax

__all__ = ["AutoCheckpoint"]

_MARKER = "DONE"


class AutoCheckpoint:
    def __init__(self, trainer, directory, every_steps=500, keep=2,
                 on_preemption=True, signals=(signal.SIGTERM,)):
        self.trainer = trainer
        self.directory = str(directory)
        self.every_steps = int(every_steps)
        self.keep = int(keep)
        self._save_pending = False     # cleared once the boundary save runs
        self._preempted = False        # sticky: "a signal arrived"
        self._prev_handlers = {}
        os.makedirs(self.directory, exist_ok=True)
        if on_preemption:
            # the handler holds only a WEAK reference: the process-global
            # signal table must not keep the trainer (the largest object
            # in the program) alive after the AutoCheckpoint is dropped
            ref = weakref.ref(self)

            def _handler(signum, frame, _ref=ref):
                obj = _ref()
                if obj is not None:
                    obj._save_pending = True
                    obj._preempted = True
            for sig in signals:
                try:
                    self._prev_handlers[sig] = signal.signal(sig, _handler)
                except (ValueError, OSError):
                    pass               # non-main thread / restricted env

    @property
    def preempted(self):
        """Sticky: True once a preemption signal has arrived (the boundary
        save does NOT clear it — training loops break on it). Use
        clear_preempted() if the grace window was rescinded."""
        return self._preempted

    def clear_preempted(self):
        self._preempted = False
        self._save_pending = False

    def close(self):
        """Restore previous signal handlers."""
        for sig, h in self._prev_handlers.items():
            try:
                signal.signal(sig, h)
            except (ValueError, OSError):
                pass
        self._prev_handlers = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # --------------------------------------------------------------- steps
    def step(self, *args, **kwargs):
        loss = self.trainer.step(*args, **kwargs)
        n = int(self.trainer.num_update)
        if self._save_pending or (
                self.every_steps > 0 and n % self.every_steps == 0):
            self.save()
            self._save_pending = False  # one boundary save per signal —
            #                             NOT one per subsequent step
        return loss

    # --------------------------------------------------------- checkpoints
    def _step_dir(self, n):
        return os.path.join(self.directory, f"step_{n:010d}")

    def save(self):
        """Checkpoint now (also called automatically by step())."""
        n = int(self.trainer.num_update)
        d = self._step_dir(n)
        self.trainer.save_states(d)
        # marker AFTER a successful save: restore_latest ignores dirs
        # without it, so a kill mid-save can never be resumed from
        if jax.process_index() == 0:
            with open(os.path.join(d, _MARKER), "w") as f:
                f.write(str(n))
        self._retain()
        return d

    def _complete_steps(self):
        out = []
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for e in entries:
            if e.startswith("step_") and os.path.exists(
                    os.path.join(self.directory, e, _MARKER)):
                try:
                    out.append(int(e[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def _retain(self):
        if jax.process_index() != 0 or self.keep <= 0:
            return
        steps = self._complete_steps()
        for n in steps[:-self.keep]:
            shutil.rmtree(self._step_dir(n), ignore_errors=True)

    def restore_latest(self):
        """Load the newest COMPLETE checkpoint into the trainer. Returns
        its step number, or None when no usable checkpoint exists."""
        steps = self._complete_steps()
        for n in reversed(steps):
            try:
                self.trainer.load_states(self._step_dir(n))
                return n
            except Exception:          # corrupt tail: fall back one
                continue
        return None
