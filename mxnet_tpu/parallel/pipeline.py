"""Pipeline parallelism: stage-sharded execution with microbatching.

Net-new vs the reference (SURVEY.md §2.4 — MXNet's only model parallelism is
coarse `group2ctx` layer placement). The schedule is expressed the TPU way:
stages live on the `pp` mesh axis, activations move stage-to-stage with
`lax.ppermute` (ICI collective-permute), and the fill/drain bubble comes
from a static `lax.scan` of length M + S - 1 — scan, not fori_loop, so the
WHOLE pipeline is differentiable and trains end-to-end under `jax.grad`.

Memory: each stage function is rematerialized (`jax.checkpoint`), so the
backward pass recomputes stage activations per microbatch and only the
stage-boundary activations are stashed — the 1F1B activation footprint
(O(M) boundaries, not O(M x layers) full stashes); the fwd/bwd compute
interleaving itself is left to XLA's scheduler.

Two entry points:
  * homogeneous (`pipeline_shard_map`): every stage runs the SAME function
    with per-stage parameters STACKED over `pp` (weights sharded S-ways).
  * heterogeneous (`pipeline_apply_hetero` / `PipelineTrainer`): per-stage
    DIFFERENT functions (embed / encoder blocks / ...) selected by
    `lax.switch` on the stage index. Parameters are replicated (compute
    shards over stages, weight memory does not) — the standard trade for
    branchy SPMD pipelines; use the homogeneous path when stages repeat.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["pipeline_apply", "pipeline_shard_map", "pipeline_apply_hetero",
           "PipelineTrainer"]


def _schedule(n, sid, M, axis_name, step_fn, state0):
    """Shared fill/drain scan. step_fn(t, x_state) -> y; returns (M, ...)
    last-stage outputs replicated across stages. Differentiable."""
    steps = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(state, t):
        y = step_fn(t, state)
        state = lax.ppermute(y, axis_name, perm)
        return state, y

    _, ys = lax.scan(body, state0, jnp.arange(steps))
    # microbatch m leaves the last stage at step m + n - 1
    outs = ys[n - 1:]
    # broadcast the last stage's outputs to every stage (differentiable:
    # the transpose of this masked psum routes cotangents back to stage n-1)
    outs = lax.psum(jnp.where(sid == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp",
                   remat=True):
    """Homogeneous pipeline body (run inside shard_map). stage_params: this
    device's stage parameters; microbatches: (M, mb, ...) replicated.
    Returns (M, mb, ...) outputs of the LAST stage, replicated."""
    n = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def step(t, state):
        mb_idx = jnp.clip(t, 0, M - 1)
        x_in = jnp.where(
            sid == 0,
            lax.dynamic_index_in_dim(microbatches, mb_idx, 0, keepdims=False),
            state)
        return fn(stage_params, x_in)

    state0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    return _schedule(n, sid, M, axis_name, step, state0)


def pipeline_shard_map(stage_fn, stacked_params, microbatches, mesh=None,
                       axis_name="pp", remat=True):
    """Top-level homogeneous helper: stacked_params pytree with leading
    stage dim sharded over `pp`; microbatches (M, mb, ...) replicated."""
    from jax import shard_map

    mesh = mesh or current_mesh()
    pspec = jax.tree.map(lambda _: P(axis_name), stacked_params)

    def fn(params_local, mb):
        params_local = jax.tree.map(lambda a: a[0], params_local)  # drop stage dim
        return pipeline_apply(stage_fn, params_local, mb, axis_name, remat)

    return shard_map(fn, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                     check_vma=False)(stacked_params, microbatches)


def pipeline_apply_hetero(stage_fns, stage_params, microbatch_inputs,
                          act_shape_dtype, axis_name="pp", remat=True,
                          rng=None):
    """Heterogeneous pipeline body (run inside shard_map).

    stage_fns: list of S callables. stage_fns[0](params[0], *mb_inputs) maps
    one microbatch of RAW inputs (tokens etc.) to an activation; every later
    stage_fns[i](params[i], act) maps activation -> activation of the SAME
    shape (the ppermute carrier). stage_params: per-stage pytrees,
    replicated on every device. microbatch_inputs: tuple of (M, mb, ...)
    arrays. act_shape_dtype: (shape, dtype) of the carried activation.
    rng: optional PRNG key; each stage call receives it folded with
    (step, stage id) as RAW key data — typed-key avals cannot cross the
    switch/remat boundary (they break scan partial-eval residual joining,
    a verified jax limitation), so stage fns take
    (params, rng_data, *inputs) and must rebuild the key themselves with
    `jax.random.wrap_key_data(rng_data, impl=...)` INSIDE the function.
    Returns (M,) + act_shape last-stage outputs, replicated."""
    n = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatch_inputs[0].shape[0]
    shape, dtype = act_shape_dtype
    if rng is None:
        rng = jax.random.key(0)

    fns = [jax.checkpoint(f) if remat else f for f in stage_fns]

    def step(t, state):
        mb_idx = jnp.clip(t, 0, M - 1)
        mb = [lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
              for x in microbatch_inputs]
        rng_data = jax.random.key_data(
            jax.random.fold_in(jax.random.fold_in(rng, t), sid))

        branches = [
            (lambda st, fn=fns[0], p=stage_params[0]:
                fn(p, rng_data, *mb).astype(dtype))
        ] + [
            (lambda st, fn=f, p=p: fn(p, rng_data, st).astype(dtype))
            for f, p in zip(fns[1:], stage_params[1:])
        ]
        return lax.switch(jnp.minimum(sid, len(branches) - 1), branches, state)

    state0 = jnp.zeros(shape, dtype)
    return _schedule(n, sid, M, axis_name, step, state0)


class PipelineTrainer:
    """Train a list of gluon stage blocks over the `pp` mesh axis.

    stages[0] consumes the raw per-microbatch inputs and produces the
    pipeline activation; stages[1:] map activation -> same-shape activation.
    `head` (optional gluon block or callable over NDArrays) runs OUTSIDE
    the pipeline on the last stage's full-batch output, followed by
    loss_fn(head_out, *labels). One jitted step: forward pipeline, loss,
    backward through the scan/ppermute schedule, optimizer.

    Reference: net-new per SURVEY §2.4 (the reference has no pipeline
    schedule; its Module/kvstore path cannot express one).
    """

    def __init__(self, stages, loss_fn, optimizer="sgd", optimizer_params=None,
                 head=None, num_microbatches=4, mesh=None, axis_name="pp"):
        from .. import optimizer as opt_mod
        from .functional_opt import FunctionalOptimizer

        self.stages = list(stages)
        self.head = head
        self.loss_fn = loss_fn
        self.mesh = mesh or current_mesh()
        self.axis = axis_name
        self.M = num_microbatches
        if self.mesh.shape.get(axis_name, 1) != len(self.stages):
            raise ValueError(
                f"pipeline axis '{axis_name}' has "
                f"{self.mesh.shape.get(axis_name, 1)} devices but "
                f"{len(self.stages)} stages were given; they must match "
                "(extra stages would silently never run)")
        self._opt = opt_mod.create(optimizer, **(optimizer_params or {})) \
            if isinstance(optimizer, str) else optimizer
        self._fopt_cls = FunctionalOptimizer
        self.num_update = 0
        self._step_cache = {}
        self._ready = False

    def _setup(self):
        from ..gluon.block import functional_call

        self._stage_fns = []
        self._stage_params = []
        names = []
        for si, blk in enumerate(self.stages):
            pure, gp, aux = functional_call(blk, train=True)
            if aux:
                raise NotImplementedError(
                    "aux state (BatchNorm moving stats) inside pipeline "
                    "stages is not supported; use LayerNorm")
            self._stage_fns.append(pure)
            self._stage_params.append(gp)
            names += [f"stage{si}.{n}" for n, _ in gp]
        head_gp = []
        self._head_fn = None
        self._head_plain = None
        if self.head is not None:
            if hasattr(self.head, "_param_lists"):
                head_pure, head_gp, head_aux = functional_call(
                    self.head, train=True)
                if head_aux:
                    raise NotImplementedError("aux state in pipeline head")
                self._head_fn = head_pure
            elif callable(self.head):
                self._head_plain = self.head     # parameterless NDArray fn
            else:
                raise TypeError(
                    f"head must be a gluon block or callable, got "
                    f"{type(self.head).__name__}")
        self._head_params = head_gp
        names += [f"head.{n}" for n, _ in head_gp]
        self.fopt = self._fopt_cls(self._opt, names)

        flat = [p.data()._data for gp in self._stage_params for _, p in gp]
        flat += [p.data()._data for _, p in head_gp]
        from . import specs as _specs
        rep = _specs.replicated(self.mesh)
        self._rep = rep
        self.params = [jax.device_put(d, rep) for d in flat]
        self.opt_state = [tuple(jax.device_put(z, rep) for z in st)
                          for st in self.fopt.init(self.params)]
        self._ready = True

    def _split_params(self, flat):
        """flat list -> (per-stage lists, head list)."""
        out, i = [], 0
        for gp in self._stage_params:
            out.append(list(flat[i:i + len(gp)]))
            i += len(gp)
        return out, list(flat[i:])

    def _build_step(self, n_data, act_sd):
        from jax import shard_map
        from ..ndarray import NDArray
        from .. import _engine
        from .trainer import call_loss

        M, axis = self.M, self.axis
        stage_fns = self._stage_fns
        head_fn = self._head_fn
        head_plain = self._head_plain
        loss_fn = self.loss_fn
        fopt = self.fopt
        mesh = self.mesh

        from .. import random as _random
        impl = jax.random.key_impl(_random.get_state())

        def fwd_pipeline(stage_param_lists, mb_inputs, rng):
            def make_stage(pure):
                def f(params, rng_data, *xs):
                    # rebuild the typed key INSIDE the (checkpointed) stage
                    # so no key-typed aval becomes a switch-branch residual
                    key = jax.random.wrap_key_data(rng_data, impl=impl)
                    outs, _ = pure(params, [], key,
                                   *[jnp.asarray(x) for x in xs])
                    return outs[0]
                return f

            fns = [make_stage(p) for p in stage_fns]
            return pipeline_apply_hetero(
                fns, stage_param_lists, tuple(mb_inputs), act_sd, axis,
                rng=rng)

        sharded_fwd = shard_map(
            fwd_pipeline, mesh=mesh,
            in_specs=(P(), P(), P()), out_specs=P(), check_vma=False)

        def step(params, opt_state, t, lr, rng, *batch):
            data, labels = batch[:n_data], batch[n_data:]

            def loss_of(flat):
                stage_lists, head_list = self._split_params(flat)
                # (B, ...) -> (M, mb, ...)
                mbs = [d.reshape((M, d.shape[0] // M) + d.shape[1:])
                       for d in data]
                acts = sharded_fwd(stage_lists, mbs, rng)  # (M, mb, ...)
                full = acts.reshape((-1,) + acts.shape[2:])
                if head_fn is not None:
                    outs, _ = head_fn(head_list, [], rng, full)
                    out = outs[0]
                elif head_plain is not None:
                    prev = _engine.set_recording(False)
                    try:
                        out_nd = head_plain(NDArray(full))
                    finally:
                        _engine.set_recording(prev)
                    out = out_nd._data if isinstance(out_nd, NDArray) else out_nd
                else:
                    out = full
                return call_loss(loss_fn, rng, [out], labels)

            loss, grads = jax.value_and_grad(loss_of)(list(params))
            new_params, new_opt = fopt.apply(params, grads, opt_state, t, lr)
            return loss, new_params, new_opt

        return jax.jit(step, donate_argnums=(0, 1))

    def _probe_act(self, data):
        """Eager forward through the stages to learn the activation shape
        for THIS input geometry (per-shape: seq-length changes change the
        carrier shape, so one probe at init is not enough)."""
        from .. import _engine
        if self._ready:
            # the blocks' own arrays were donated into the jitted step;
            # refresh them from live device state before probing eagerly
            self.sync_to_block()
        prev = _engine.set_recording(False)
        try:
            x = self.stages[0](*data)
            for s in self.stages[1:]:
                x = s(x)
        finally:
            _engine.set_recording(prev)
        return ((data[0].shape[0] // self.M,) + x.shape[1:], x._data.dtype)

    def step(self, data, labels):
        from ..ndarray import NDArray
        from .. import random as _random

        data = data if isinstance(data, (list, tuple)) else [data]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        probed = None
        if not self._ready:
            probed = self._probe_act(data)  # resolves deferred param shapes
            self._setup()
        batch = [b._data if isinstance(b, NDArray) else jnp.asarray(b)
                 for b in list(data) + list(labels)]
        if batch[0].shape[0] % self.M:
            raise ValueError(
                f"batch {batch[0].shape[0]} not divisible by "
                f"num_microbatches={self.M}")
        shapes = tuple(b.shape for b in batch)
        key = (len(data), shapes)
        if key not in self._step_cache:
            act_sd = probed or self._probe_act(data)
            self._step_cache[key] = self._build_step(len(data), act_sd)
        self.num_update += 1
        t = jnp.asarray(self.num_update, jnp.float32)
        lr = jnp.asarray(self.fopt.lr_at(self.num_update), jnp.float32)
        loss, self.params, self.opt_state = self._step_cache[key](
            self.params, self.opt_state, t, lr, _random.next_key(), *batch)
        return NDArray(loss)

    def sync_to_block(self):
        stage_lists, head_list = self._split_params(self.params)
        for gp, vals in zip(self._stage_params, stage_lists):
            for (_, p), v in zip(gp, vals):
                p.data()._data = v
        for (_, p), v in zip(self._head_params, head_list):
            p.data()._data = v
