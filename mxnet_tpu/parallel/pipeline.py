"""Pipeline parallelism: stage-sharded execution with microbatching.

Net-new vs the reference (SURVEY.md §2.4 — MXNet's only model parallelism is
coarse `group2ctx` layer placement). The schedule is expressed the TPU way:
stages live on the `pp` mesh axis, activations move stage-to-stage with
`lax.ppermute` (ICI collective-permute), and the fill/drain bubble comes
from a static `lax.scan` of length M + S - 1 — scan, not fori_loop, so the
WHOLE pipeline is differentiable and trains end-to-end under `jax.grad`.

Memory: each stage function is rematerialized (`jax.checkpoint`), so the
backward pass recomputes stage activations per microbatch and only the
stage-boundary activations are stashed — the 1F1B activation footprint
(O(M) boundaries, not O(M x layers) full stashes); the fwd/bwd compute
interleaving itself is left to XLA's scheduler.

Two entry points:
  * homogeneous (`pipeline_shard_map`): every stage runs the SAME function
    with per-stage parameters STACKED over `pp` (weights sharded S-ways).
  * heterogeneous (`pipeline_apply_hetero` / `PipelineTrainer`): per-stage
    DIFFERENT functions (embed / encoder blocks / ...) selected by
    `lax.switch` on the stage index. Parameters are replicated (compute
    shards over stages, weight memory does not) — the standard trade for
    branchy SPMD pipelines; use the homogeneous path when stages repeat.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["pipeline_apply", "pipeline_shard_map", "pipeline_apply_hetero",
           "PipelineTrainer", "SeqPipelineTrainer"]


def _schedule(n, sid, M, axis_name, step_fn, state0):
    """Shared fill/drain scan. step_fn(t, x_state) -> y; returns (M, ...)
    last-stage outputs replicated across stages. Differentiable."""
    steps = M + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(state, t):
        y = step_fn(t, state)
        state = lax.ppermute(y, axis_name, perm)
        return state, y

    _, ys = lax.scan(body, state0, jnp.arange(steps))
    # microbatch m leaves the last stage at step m + n - 1
    outs = ys[n - 1:]
    # broadcast the last stage's outputs to every stage (differentiable:
    # the transpose of this masked psum routes cotangents back to stage n-1)
    outs = lax.psum(jnp.where(sid == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp",
                   remat=True):
    """Homogeneous pipeline body (run inside shard_map). stage_params: this
    device's stage parameters; microbatches: (M, mb, ...) replicated.
    Returns (M, mb, ...) outputs of the LAST stage, replicated."""
    n = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def step(t, state):
        mb_idx = jnp.clip(t, 0, M - 1)
        x_in = jnp.where(
            sid == 0,
            lax.dynamic_index_in_dim(microbatches, mb_idx, 0, keepdims=False),
            state)
        return fn(stage_params, x_in)

    state0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    return _schedule(n, sid, M, axis_name, step, state0)


def pipeline_shard_map(stage_fn, stacked_params, microbatches, mesh=None,
                       axis_name="pp", remat=True):
    """Top-level homogeneous helper: stacked_params pytree with leading
    stage dim sharded over `pp`; microbatches (M, mb, ...) replicated."""
    from ._compat import shard_map

    mesh = mesh or current_mesh()
    pspec = jax.tree.map(lambda _: P(axis_name), stacked_params)

    def fn(params_local, mb):
        params_local = jax.tree.map(lambda a: a[0], params_local)  # drop stage dim
        return pipeline_apply(stage_fn, params_local, mb, axis_name, remat)

    return shard_map(fn, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                     check_vma=False)(stacked_params, microbatches)


def pipeline_apply_hetero(stage_fns, stage_params, microbatch_inputs,
                          act_shape_dtype, axis_name="pp", remat=True,
                          rng=None):
    """Heterogeneous pipeline body (run inside shard_map).

    stage_fns: list of S callables. stage_fns[0](params[0], *mb_inputs) maps
    one microbatch of RAW inputs (tokens etc.) to an activation; every later
    stage_fns[i](params[i], act) maps activation -> activation of the SAME
    shape (the ppermute carrier). stage_params: per-stage pytrees,
    replicated on every device. microbatch_inputs: tuple of (M, mb, ...)
    arrays. act_shape_dtype: (shape, dtype) of the carried activation.
    rng: optional PRNG key; each stage call receives it folded with
    (step, stage id) as RAW key data — typed-key avals cannot cross the
    switch/remat boundary (they break scan partial-eval residual joining,
    a verified jax limitation), so stage fns take
    (params, rng_data, *inputs) and must rebuild the key themselves with
    `jax.random.wrap_key_data(rng_data, impl=...)` INSIDE the function.
    Returns (M,) + act_shape last-stage outputs, replicated."""
    n = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatch_inputs[0].shape[0]
    shape, dtype = act_shape_dtype
    if rng is None:
        rng = jax.random.key(0)

    fns = [jax.checkpoint(f) if remat else f for f in stage_fns]

    def step(t, state):
        mb_idx = jnp.clip(t, 0, M - 1)
        mb = [lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
              for x in microbatch_inputs]
        rng_data = jax.random.key_data(
            jax.random.fold_in(jax.random.fold_in(rng, t), sid))

        branches = [
            (lambda st, fn=fns[0], p=stage_params[0]:
                fn(p, rng_data, *mb).astype(dtype))
        ] + [
            (lambda st, fn=f, p=p: fn(p, rng_data, st).astype(dtype))
            for f, p in zip(fns[1:], stage_params[1:])
        ]
        return lax.switch(jnp.minimum(sid, len(branches) - 1), branches, state)

    state0 = jnp.zeros(shape, dtype)
    return _schedule(n, sid, M, axis_name, step, state0)


from .trainer import PipelineCheckpointMixin


class SeqPipelineTrainer(PipelineCheckpointMixin):
    """Pipeline x data x sequence parallelism in one SPMD program.

    The composition the hetero PipelineTrainer cannot express: ring
    attention's sp collectives must execute UNCONDITIONALLY on every device,
    so the pipeline must be homogeneous — every pp stage runs the SAME
    function over stage-STACKED parameters (sharded over `pp`), embed and
    head run replicated across pp outside the scan (cheap: they are a small
    fraction of the compute), and dp/sp shard the batch/sequence inside the
    same shard_map. This is the long-context training schedule of SURVEY
    §5.7: pp moves layer groups across chips, sp (ring attention +
    sp-offset position embeddings, signalled via `manual_axes`) shards the
    sequence, dp the batch.

    embed: gluon block mapping raw inputs -> (B, L, E) activation.
    stages: list of structurally IDENTICAL gluon blocks (act -> act).
    head: gluon block mapping act -> outputs for loss_fn.
    data_specs/label_specs: PartitionSpecs of the raw batch arrays, e.g.
    P(('dp','fsdp'), 'sp') for token ids.
    """

    def __init__(self, embed, stages, head, loss_fn, optimizer="sgd",
                 optimizer_params=None, num_microbatches=2, mesh=None,
                 axis_name="pp", data_specs=None, label_specs=None,
                 remat=True):
        from .. import optimizer as opt_mod
        from .functional_opt import FunctionalOptimizer

        self.embed, self.stages, self.head = embed, list(stages), head
        self.loss_fn = loss_fn
        self.mesh = mesh or current_mesh()
        self.axis = axis_name
        self.M = num_microbatches
        self.remat = remat
        self._data_specs = list(data_specs or [])
        self._label_specs = list(label_specs or [])
        if self.mesh.shape.get(axis_name, 1) != len(self.stages):
            raise ValueError(
                f"pipeline axis '{axis_name}' has "
                f"{self.mesh.shape.get(axis_name, 1)} devices but "
                f"{len(self.stages)} stages were given; they must match")
        self._opt = opt_mod.create(optimizer, **(optimizer_params or {})) \
            if isinstance(optimizer, str) else optimizer
        self._fopt_cls = FunctionalOptimizer
        self.num_update = 0
        self._step_cache = {}
        self._setup()

    def _setup(self):
        from ..gluon.block import functional_call

        def pure(blk, what):
            fn, gp, aux = functional_call(blk, train=True)
            if aux:
                raise NotImplementedError(
                    f"aux state (BatchNorm stats) in pipeline {what}")
            return fn, gp

        self._embed_fn, embed_gp = pure(self.embed, "embed")
        stage_fns, stage_gps = zip(*[pure(s, "stage") for s in self.stages])
        self._stage_fn = stage_fns[0]
        ref_names = [n for n, _ in stage_gps[0]]
        for gp in stage_gps[1:]:
            if [n for n, _ in gp] != ref_names:
                raise ValueError("homogeneous pipeline stages must be "
                                 "structurally identical")
        self._head_fn, head_gp = pure(self.head, "head")
        self._embed_gp, self._stage_gps, self._head_gp = \
            embed_gp, stage_gps, head_gp

        names = [f"embed.{n}" for n, _ in embed_gp]
        names += [f"stages.{n}" for n in ref_names]
        names += [f"head.{n}" for n, _ in head_gp]
        self.fopt = self._fopt_cls(self._opt, names)

        from . import specs as _specs
        rep = _specs.replicated(self.mesh)
        self._rep = rep
        self._n_embed, self._n_stage = len(embed_gp), len(ref_names)
        # stage params stacked over a leading stage dim, sharded over pp —
        # device pp=i holds only ITS stage's weights (true pipeline memory)
        flat = [jax.device_put(p.data()._data, rep) for _, p in embed_gp]
        self._stack_shard = []
        for li in range(self._n_stage):
            leaves = [gp[li][1].data()._data for gp in stage_gps]
            stacked = jnp.stack(leaves)
            sh = jax.sharding.NamedSharding(
                self.mesh, P(*((self.axis,) + (None,) * (stacked.ndim - 1))))
            self._stack_shard.append(sh)
            flat.append(jax.device_put(stacked, sh))
        flat += [jax.device_put(p.data()._data, rep) for _, p in head_gp]
        self.params = flat
        self._pshard = ([rep] * self._n_embed + self._stack_shard +
                        [rep] * len(head_gp))
        self.opt_state = [
            tuple(jax.device_put(z, s) for z in st)
            for st, s in zip(self.fopt.init(self.params), self._pshard)]

    def _build_step(self, n_data, n_label):
        from ._compat import shard_map
        from .. import random as _random
        from .trainer import call_loss

        M, axis, mesh = self.M, self.axis, self.mesh
        embed_fn, stage_fn, head_fn = \
            self._embed_fn, self._stage_fn, self._head_fn
        loss_fn = self.loss_fn
        fopt = self.fopt
        remat = self.remat
        ne, ns = self._n_embed, self._n_stage
        data_axes = tuple(a for a in ("dp", "fsdp", "sp")
                          if mesh.shape.get(a, 1) > 1)

        dspecs = (self._data_specs + [P()] * n_data)[:n_data]
        lspecs = (self._label_specs + [P()] * n_label)[:n_label]
        stack_specs = [P(*((axis,) + (None,) * (s.ndim - 1)))
                       for s in self.params[ne:ne + ns]]

        def body(ep, sp_, hp, rng, *arrs):
            data_l, labels_l = arrs[:n_data], arrs[n_data:]
            outs, _ = embed_fn(ep, [], jax.random.fold_in(rng, 7),
                               *[jnp.asarray(a) for a in data_l])
            x0 = outs[0]                            # (B_loc, L_loc, E)
            mb = x0.shape[0] // M
            mbs = x0.reshape((M, mb) + x0.shape[1:])
            sp_local = [a[0] for a in sp_]          # drop the stage dim

            def sfn(pl, x):
                o, _ = stage_fn(pl, [], jax.random.fold_in(rng, 11), x)
                return o[0]

            acts = pipeline_apply(sfn, sp_local, mbs, axis, remat=remat)
            full = acts.reshape((-1,) + acts.shape[2:])
            houts, _ = head_fn(hp, [], jax.random.fold_in(rng, 13), full)
            loss = call_loss(loss_fn, rng, [houts[0]], list(labels_l))
            # equal-sized shards: global mean = mean of shard means
            return lax.pmean(loss, data_axes) if data_axes else loss

        sharded = shard_map(
            body, mesh=mesh,
            in_specs=([P()] * ne, stack_specs, [P()] * len(self._head_gp),
                      P(), *dspecs, *lspecs),
            out_specs=P(), check_vma=False)

        def step(params, opt_state, t, lr, rng, *batch):
            def loss_of(flat):
                return sharded(flat[:ne], flat[ne:ne + ns], flat[ne + ns:],
                               rng, *batch)

            loss, grads = jax.value_and_grad(loss_of)(list(params))
            new_params, new_opt = fopt.apply(params, grads, opt_state, t, lr)
            return loss, new_params, new_opt

        return jax.jit(step, donate_argnums=(0, 1))

    def step(self, data, labels):
        from ..ndarray import NDArray
        from .. import random as _random
        from .mesh import manual_axes

        data = data if isinstance(data, (list, tuple)) else [data]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        batch = [b._data if isinstance(b, NDArray) else jnp.asarray(b)
                 for b in list(data) + list(labels)]
        key = (len(data), tuple(b.shape for b in batch))
        if key not in self._step_cache:
            self._step_cache[key] = self._build_step(len(data), len(labels))
        self.num_update += 1
        t = jnp.asarray(self.num_update, jnp.float32)
        lr = jnp.asarray(self.fopt.lr_at(self.num_update), jnp.float32)
        # sp is shard_map-controlled while the step traces: stage blocks'
        # ring attention and sp position embeddings run per-shard
        with manual_axes("sp"):
            loss, self.params, self.opt_state = self._step_cache[key](
                self.params, self.opt_state, t, lr, _random.next_key(),
                *batch)
        return NDArray(loss)

    def sync_to_block(self):
        ne, ns = self._n_embed, self._n_stage
        for (_, p), v in zip(self._embed_gp, self.params[:ne]):
            p.data()._data = v
        for li, stacked in enumerate(self.params[ne:ne + ns]):
            for si, gp in enumerate(self._stage_gps):
                gp[li][1].data()._data = stacked[si]
        for (_, p), v in zip(self._head_gp, self.params[ne + ns:]):
            p.data()._data = v


class PipelineTrainer(PipelineCheckpointMixin):
    """Train a list of gluon stage blocks over the `pp` mesh axis.

    stages[0] consumes the raw per-microbatch inputs and produces the
    pipeline activation; stages[1:] map activation -> same-shape activation.
    `head` (optional gluon block or callable over NDArrays) runs OUTSIDE
    the pipeline on the last stage's full-batch output, followed by
    loss_fn(head_out, *labels). One jitted step: forward pipeline, loss,
    backward through the scan/ppermute schedule, optimizer.

    Reference: net-new per SURVEY §2.4 (the reference has no pipeline
    schedule; its Module/kvstore path cannot express one).
    """

    def __init__(self, stages, loss_fn, optimizer="sgd", optimizer_params=None,
                 head=None, num_microbatches=4, mesh=None, axis_name="pp",
                 data_specs=None, act_spec=None):
        """data_specs: optional per-input PartitionSpecs over the (mb, ...)
        microbatch dims (e.g. P(('dp','fsdp')) for tokens) — the pipeline
        then runs data-sharded INSIDE its shard_map, composing pp with dp.
        act_spec: PartitionSpec of the activation carrier's (mb, ...) dims;
        required when data_specs shard anything. 'sp' specs are rejected
        (collectives cannot live inside the stage switch) — use
        SeqPipelineTrainer for pp x sp."""
        from .. import optimizer as opt_mod
        from .functional_opt import FunctionalOptimizer

        self.stages = list(stages)
        self._data_specs = list(data_specs) if data_specs else None
        self._act_spec = act_spec
        if self._data_specs and act_spec is None:
            raise ValueError("act_spec is required when data_specs shard "
                             "the microbatch inputs")
        for spec in (self._data_specs or []) + \
                ([act_spec] if act_spec is not None else []):
            for ax in spec:
                axes = ax if isinstance(ax, tuple) else (ax,)
                if "sp" in axes:
                    raise ValueError(
                        "sequence parallelism cannot run inside the "
                        "heterogeneous pipeline: ring attention's ppermutes "
                        "would sit inside the per-stage lax.switch, and "
                        "collectives inside divergent control flow are "
                        "illegal SPMD. Use SeqPipelineTrainer (homogeneous "
                        "stages; collectives execute uniformly)")
        self.head = head
        self.loss_fn = loss_fn
        self.mesh = mesh or current_mesh()
        self.axis = axis_name
        self.M = num_microbatches
        if self.mesh.shape.get(axis_name, 1) != len(self.stages):
            raise ValueError(
                f"pipeline axis '{axis_name}' has "
                f"{self.mesh.shape.get(axis_name, 1)} devices but "
                f"{len(self.stages)} stages were given; they must match "
                "(extra stages would silently never run)")
        self._opt = opt_mod.create(optimizer, **(optimizer_params or {})) \
            if isinstance(optimizer, str) else optimizer
        self._fopt_cls = FunctionalOptimizer
        self.num_update = 0
        self._step_cache = {}
        self._ready = False

    def _setup(self):
        from ..gluon.block import functional_call

        self._stage_fns = []
        self._stage_params = []
        names = []
        for si, blk in enumerate(self.stages):
            pure, gp, aux = functional_call(blk, train=True)
            if aux:
                raise NotImplementedError(
                    "aux state (BatchNorm moving stats) inside pipeline "
                    "stages is not supported; use LayerNorm")
            self._stage_fns.append(pure)
            self._stage_params.append(gp)
            names += [f"stage{si}.{n}" for n, _ in gp]
        head_gp = []
        self._head_fn = None
        self._head_plain = None
        if self.head is not None:
            if hasattr(self.head, "_param_lists"):
                head_pure, head_gp, head_aux = functional_call(
                    self.head, train=True)
                if head_aux:
                    raise NotImplementedError("aux state in pipeline head")
                self._head_fn = head_pure
            elif callable(self.head):
                self._head_plain = self.head     # parameterless NDArray fn
            else:
                raise TypeError(
                    f"head must be a gluon block or callable, got "
                    f"{type(self.head).__name__}")
        self._head_params = head_gp
        names += [f"head.{n}" for n, _ in head_gp]
        self.fopt = self._fopt_cls(self._opt, names)

        flat = [p.data()._data for gp in self._stage_params for _, p in gp]
        flat += [p.data()._data for _, p in head_gp]
        from . import specs as _specs
        rep = _specs.replicated(self.mesh)
        self._rep = rep
        self.params = [jax.device_put(d, rep) for d in flat]
        self.opt_state = [tuple(jax.device_put(z, rep) for z in st)
                          for st in self.fopt.init(self.params)]
        self._ready = True

    def _split_params(self, flat):
        """flat list -> (per-stage lists, head list)."""
        out, i = [], 0
        for gp in self._stage_params:
            out.append(list(flat[i:i + len(gp)]))
            i += len(gp)
        return out, list(flat[i:])

    def _build_step(self, n_data, act_sd):
        from ._compat import shard_map
        from ..ndarray import NDArray
        from .. import _engine
        from .trainer import call_loss

        M, axis = self.M, self.axis
        stage_fns = self._stage_fns
        head_fn = self._head_fn
        head_plain = self._head_plain
        loss_fn = self.loss_fn
        fopt = self.fopt
        mesh = self.mesh

        from .. import random as _random
        impl = jax.random.key_impl(_random.get_state())

        # local activation-carrier shape: divide the probed global dims by
        # the mesh-axis sizes named in act_spec (dim 0 of act_sd is mb)
        local_act = act_sd
        if self._act_spec is not None:
            shape = list(act_sd[0])
            for d, ax in enumerate(self._act_spec):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                n = 1
                for a in axes:
                    n *= mesh.shape.get(a, 1)
                if shape[d] % n:
                    raise ValueError(
                        f"activation dim {d} ({shape[d]}) not divisible by "
                        f"axis product {n} of spec {self._act_spec}")
                shape[d] //= n
            local_act = (tuple(shape), act_sd[1])

        def fwd_pipeline(stage_param_lists, mb_inputs, rng):
            def make_stage(pure):
                def f(params, rng_data, *xs):
                    # rebuild the typed key INSIDE the (checkpointed) stage
                    # so no key-typed aval becomes a switch-branch residual
                    key = jax.random.wrap_key_data(rng_data, impl=impl)
                    outs, _ = pure(params, [], key,
                                   *[jnp.asarray(x) for x in xs])
                    return outs[0]
                return f

            fns = [make_stage(p) for p in stage_fns]
            return pipeline_apply_hetero(
                fns, stage_param_lists, tuple(mb_inputs), local_act, axis,
                rng=rng)

        if self._data_specs:
            mb_specs = [P(None, *ds) for ds in self._data_specs]
            out_spec = P(None, *self._act_spec)
        else:
            mb_specs = [P() for _ in range(n_data)]
            out_spec = P()
        sharded_fwd = shard_map(
            fwd_pipeline, mesh=mesh,
            in_specs=(P(), mb_specs, P()), out_specs=out_spec,
            check_vma=False)

        def step(params, opt_state, t, lr, rng, *batch):
            data, labels = batch[:n_data], batch[n_data:]

            def loss_of(flat):
                stage_lists, head_list = self._split_params(flat)
                # (B, ...) -> (M, mb, ...)
                mbs = [d.reshape((M, d.shape[0] // M) + d.shape[1:])
                       for d in data]
                acts = sharded_fwd(stage_lists, mbs, rng)  # (M, mb, ...)
                full = acts.reshape((-1,) + acts.shape[2:])
                if head_fn is not None:
                    outs, _ = head_fn(head_list, [], rng, full)
                    out = outs[0]
                elif head_plain is not None:
                    prev = _engine.set_recording(False)
                    try:
                        out_nd = head_plain(NDArray(full))
                    finally:
                        _engine.set_recording(prev)
                    out = out_nd._data if isinstance(out_nd, NDArray) else out_nd
                else:
                    out = full
                return call_loss(loss_fn, rng, [out], labels)

            loss, grads = jax.value_and_grad(loss_of)(list(params))
            new_params, new_opt = fopt.apply(params, grads, opt_state, t, lr)
            return loss, new_params, new_opt

        return jax.jit(step, donate_argnums=(0, 1))

    def _probe_act(self, data):
        """Eager forward through the stages to learn the activation shape
        for THIS input geometry (per-shape: seq-length changes change the
        carrier shape, so one probe at init is not enough)."""
        from .. import _engine
        if self._ready:
            # the blocks' own arrays were donated into the jitted step;
            # refresh them from live device state before probing eagerly
            self.sync_to_block()
        prev = _engine.set_recording(False)
        try:
            x = self.stages[0](*data)
            for s in self.stages[1:]:
                x = s(x)
        finally:
            _engine.set_recording(prev)
        return ((data[0].shape[0] // self.M,) + x.shape[1:], x._data.dtype)

    def step(self, data, labels):
        from ..ndarray import NDArray
        from .. import random as _random

        data = data if isinstance(data, (list, tuple)) else [data]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        probed = None
        if not self._ready:
            probed = self._probe_act(data)  # resolves deferred param shapes
            self._setup()
        batch = [b._data if isinstance(b, NDArray) else jnp.asarray(b)
                 for b in list(data) + list(labels)]
        if batch[0].shape[0] % self.M:
            raise ValueError(
                f"batch {batch[0].shape[0]} not divisible by "
                f"num_microbatches={self.M}")
        shapes = tuple(b.shape for b in batch)
        key = (len(data), shapes)
        if key not in self._step_cache:
            act_sd = probed or self._probe_act(data)
            self._step_cache[key] = self._build_step(len(data), act_sd)
        self.num_update += 1
        t = jnp.asarray(self.num_update, jnp.float32)
        lr = jnp.asarray(self.fopt.lr_at(self.num_update), jnp.float32)
        loss, self.params, self.opt_state = self._step_cache[key](
            self.params, self.opt_state, t, lr, _random.next_key(), *batch)
        return NDArray(loss)

    def sync_to_block(self):
        stage_lists, head_list = self._split_params(self.params)
        for gp, vals in zip(self._stage_params, stage_lists):
            for (_, p), v in zip(gp, vals):
                p.data()._data = v
        for (_, p), v in zip(self._head_params, head_list):
            p.data()._data = v
