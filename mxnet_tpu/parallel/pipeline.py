"""Pipeline parallelism: stage-sharded execution with microbatching.

Net-new vs the reference (SURVEY.md §2.4 — MXNet's only model parallelism is
coarse `group2ctx` layer placement). GPipe-style schedule expressed the TPU
way: stages live on the `pp` mesh axis, activations move stage-to-stage with
`lax.ppermute` (ICI collective-permute), and the fill/drain bubble comes from
a static fori_loop of length M + S - 1.

Constraint (standard for collective pipelines): every stage maps activations
of one fixed shape to the same shape.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["pipeline_apply", "pipeline_shard_map"]


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp"):
    """Run inside shard_map. stage_params: this device's stage parameters;
    microbatches: (M, mb, ...) the full input, replicated across stages.
    Returns (M, mb, ...) outputs of the LAST stage, replicated."""
    n = lax.psum(1, axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    steps = M + n - 1
    mb_shape = microbatches.shape[1:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    state = jnp.zeros(mb_shape, microbatches.dtype)
    outs = jnp.zeros((M,) + mb_shape, microbatches.dtype)

    def body(t, carry):
        state, outs = carry
        mb_idx = jnp.clip(t, 0, M - 1)
        x_in = jnp.where(sid == 0,
                         lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                                  keepdims=False),
                         state)
        y = stage_fn(stage_params, x_in)
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        write = jnp.logical_and(sid == n - 1, t >= n - 1)
        prev = lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, y, prev), out_idx, 0)
        state = lax.ppermute(y, axis_name, perm)
        return state, outs

    state, outs = lax.fori_loop(0, steps, body, (state, outs))
    # broadcast the last stage's outputs to every stage
    outs = lax.psum(jnp.where(sid == n - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_shard_map(stage_fn, stacked_params, microbatches, mesh=None,
                       axis_name="pp"):
    """Top-level helper: stacked_params pytree with leading stage dim sharded
    over `pp`; microbatches (M, mb, ...) replicated."""
    from jax import shard_map

    mesh = mesh or current_mesh()
    pspec = jax.tree.map(lambda _: P(axis_name), stacked_params)

    def fn(params_local, mb):
        params_local = jax.tree.map(lambda a: a[0], params_local)  # drop stage dim
        return pipeline_apply(stage_fn, params_local, mb, axis_name)

    return shard_map(fn, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                     check_vma=False)(stacked_params, microbatches)
