"""Ulysses-style sequence parallelism: all-to-all head↔sequence reshard.

Net-new vs the reference (SURVEY.md §5.7). Complementary to ring attention:
instead of rotating K/V around the ring, two `lax.all_to_all`s reshard the
activations so each device sees the FULL sequence for a SUBSET of heads —
then any local attention kernel (the Pallas flash kernel included) runs
unchanged. Cost: 2 all-to-alls of the qkv/out activations; wins over ring
when head count ≥ devices and the per-device sequence is short enough that
ring latency dominates.

Layout contract (inside shard_map over `sp`):
  in:  q,k,v (B, H, L/n, D)  — all heads, local sequence shard
  mid: (B, H/n, L, D)        — local heads, full sequence
  out: (B, H, L/n, D)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["ulysses_attention", "ulysses_self_attention", "seq_to_heads",
           "heads_to_seq"]


def seq_to_heads(x, axis_name):
    """(B, H, L/n, D) → (B, H/n, L, D): split heads across the axis, gather
    the sequence (one all_to_all on ICI)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def heads_to_seq(x, axis_name):
    """(B, H/n, L, D) → (B, H, L/n, D): inverse reshard."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _local_attention(q, k, v, mask, causal, sm_scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask[:, None, None, :].astype(bool), s, -1e30)
    if causal:
        L = q.shape[2]
        idx = jnp.arange(L)
        s = jnp.where(idx[None, None, :, None] >= idx[None, None, None, :],
                      s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, mask=None, causal=False,
                      sm_scale=None, attn_fn=None):
    """Call INSIDE shard_map with sequence sharded on `axis_name`.

    q,k,v: (B, H, L_local, D); H must be divisible by the axis size.
    mask: (B, L_local) padding mask (True = attend). `attn_fn` overrides the
    local kernel (signature (q,k,v,mask,causal,sm_scale) on full-seq blocks),
    e.g. to drop in the Pallas flash kernel.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = lax.psum(1, axis_name)
    if q.shape[1] % n:
        raise ValueError(f"num_heads {q.shape[1]} not divisible by "
                         f"axis size {n}")
    q_f = seq_to_heads(q, axis_name)
    k_f = seq_to_heads(k, axis_name)
    v_f = seq_to_heads(v, axis_name)
    full_mask = None
    if mask is not None:
        # (B, L/n) -> (B, L): every device needs the whole padding mask
        full_mask = lax.all_gather(mask, axis_name, axis=1, tiled=True)
    fn = attn_fn or _local_attention
    out = fn(q_f, k_f, v_f, full_mask, causal, sm_scale)
    return heads_to_seq(out, axis_name)


def ulysses_self_attention(q, k, v, mask=None, causal=False, mesh=None,
                           axis_name="sp"):
    """shard_map wrapper over global (B, H, L, D) tensors, L sharded on
    `axis_name` (mirror of ring_self_attention)."""
    from ._compat import shard_map

    mesh = mesh or current_mesh()
    qspec = P(None, None, axis_name, None)
    mspec = P(None, axis_name)
    if mask is not None:
        fn = shard_map(
            lambda q_, k_, v_, m_: ulysses_attention(
                q_, k_, v_, axis_name, mask=m_, causal=causal),
            mesh=mesh, in_specs=(qspec, qspec, qspec, mspec),
            out_specs=qspec, check_vma=False)
        return fn(q, k, v, mask)
    fn = shard_map(
        lambda q_, k_, v_: ulysses_attention(q_, k_, v_, axis_name,
                                             causal=causal),
        mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
        check_vma=False)
    return fn(q, k, v)
