"""Device mesh construction.

The TPU-native replacement for the reference's transport stack
(`src/kvstore/comm.h` CommDevice, `kvstore_nccl.h`, `3rdparty/ps-lite/` —
SURVEY.md §2.5): no user-level transport exists; a named `jax.sharding.Mesh`
plus sharding annotations make XLA emit all collectives over ICI/DCN.

Axis vocabulary (used across parallel/ and models/):
  dp   — data parallel (batch)
  fsdp — parameter/optimizer-state sharding over the data axis (ZeRO-like;
          the TPU analog of the reference's parameter-server sharding,
          `MXNET_KVSTORE_BIGARRAY_BOUND` round-robin)
  tp   — tensor (Megatron) parallel
  sp   — sequence/context parallel (ring attention)
  pp   — pipeline stages
  ep   — expert parallel (MoE expert sharding + all_to_all dispatch)
"""
from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "MeshPlan", "current_mesh", "set_mesh", "named_sharding",
           "PartitionSpec", "local_mesh_devices", "manual_axes", "in_manual",
           "mesh_axes"]

_current = {"mesh": None}
_manual = set()


class manual_axes:
    """Mark mesh axes as already under manual (shard_map) control while
    tracing, so axis-aware library code (ring attention, sp position
    embeddings) uses per-shard collectives directly instead of opening a
    nested shard_map. SeqPipelineTrainer sets this around its jitted step;
    see `ops.nn_ops.fused_self_attention` and `models.bert` for consumers."""

    def __init__(self, *names):
        self.names = set(names)

    def __enter__(self):
        self._added = self.names - _manual
        _manual.update(self.names)
        return self

    def __exit__(self, *exc):
        _manual.difference_update(self._added)
        return False


def in_manual(name):
    """True when `name` is currently a manual (shard_map-controlled) axis."""
    return name in _manual


class MeshPlan:
    """A named parallelism plan: axis name → size. Size -1 means 'absorb the
    remaining devices' (at most one axis may be -1)."""

    def __init__(self, dp=1, fsdp=1, tp=1, sp=1, pp=1, ep=1):
        self.axes = {"dp": dp, "fsdp": fsdp, "tp": tp, "sp": sp,
                     "pp": pp, "ep": ep}

    def resolve(self, n_devices):
        sizes = dict(self.axes)
        fill = [k for k, v in sizes.items() if v == -1]
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if fill:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            sizes[fill[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"plan {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def local_mesh_devices(n=None):
    devs = jax.devices()
    return devs if n is None else devs[:n]


def make_mesh(plan=None, devices=None, **axis_sizes):
    """Build a Mesh. `make_mesh(dp=-1)` → pure data parallel over all devices;
    `make_mesh(dp=2, tp=4)` etc. Axes of size 1 are kept (harmless in specs).

    ICI note: jax.devices() order follows the physical torus; keeping the
    innermost (fastest-varying) axes for tp/sp places those collectives on
    neighbouring chips, which is what mesh_utils would do for a real slice.
    """
    if plan is None:
        plan = MeshPlan(**{k: axis_sizes.get(k, 1) for k in
                           ("dp", "fsdp", "tp", "sp", "pp", "ep")}) \
            if axis_sizes else MeshPlan(dp=-1)
    devices = devices or jax.devices()
    sizes = plan.resolve(len(devices))
    # order: pp outermost (cross-slice ok), then dp, fsdp, ep, sp, tp innermost
    order = ["pp", "dp", "fsdp", "ep", "sp", "tp"]
    shape = [sizes[a] for a in order]
    arr = np.asarray(devices[:math.prod(shape)]).reshape(shape)
    mesh = Mesh(arr, axis_names=tuple(order))
    set_mesh(mesh)
    return mesh


def set_mesh(mesh):
    _current["mesh"] = mesh


def current_mesh():
    if _current["mesh"] is None:
        make_mesh()
    return _current["mesh"]


def mesh_axes(mesh):
    """{axis name: size} for a Mesh (JSON-able; axis order preserved).
    The topology identity the checkpoint manifest records — compared at
    restore to decide whether a redistribution is needed."""
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def named_sharding(*spec, mesh=None):
    """NamedSharding on the active mesh; `named_sharding('dp', None)` etc."""
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, PartitionSpec(*spec))
