"""jax version compatibility for the parallel layer.

Two drifts covered, so a jax upgrade/downgrade cannot take out the whole
parallelism layer (ring/ulysses attention, MoE, pipeline) at call time:

  * `shard_map` graduated from `jax.experimental.shard_map` to a
    top-level `jax.shard_map` export — exactly one spelling exists per
    version.
  * its replication-check kwarg was renamed `check_rep` → `check_vma`;
    the wrapper translates whichever spelling the installed jax lacks.

Every shard_map call site in this package imports through here.
"""
from __future__ import annotations

import inspect as _inspect

try:
    from jax import shard_map as _sm
    # new jax: top-level export (a module in some versions, the function
    # in others — normalize to the callable)
    _shard_map = getattr(_sm, "shard_map", _sm)
except ImportError:                      # pragma: no cover - version path
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _KWARGS = set(_inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):          # pragma: no cover - exotic builds
    _KWARGS = None


def shard_map(*args, **kwargs):
    """jax's shard_map with the replication-check kwarg translated to
    whatever the installed version accepts."""
    if _KWARGS is not None:
        if "check_vma" in kwargs and "check_vma" not in _KWARGS \
                and "check_rep" in _KWARGS:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        elif "check_rep" in kwargs and "check_rep" not in _KWARGS \
                and "check_vma" in _KWARGS:
            kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)


__all__ = ["shard_map"]
