"""mx.reshard — cross-topology array redistribution.

A checkpoint written on an N-device mesh must restore onto an M-device
mesh (or a different data/model axis split) as a REDISTRIBUTION, not a
failure: preemption on a shrinking pod is a reshape. Grounding:

  * "Memory-efficient array redistribution through portable collective
    communication" (arxiv 2112.01075) — redistribution decomposes into a
    schedule of bounded-size moves; per-device peak memory stays
    O(src_shard + dst_shard), never O(global array), and a full
    all-gather is the last resort (only when the TARGET layout itself is
    replicated), never an intermediate.
  * "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training" (arxiv 2004.13336) — optimizer state shards like its
    parameter, so it must reshard ALONGSIDE params (including the
    fused-LAMB flat-master layout, which checkpoints in the canonical
    per-tensor form exactly so this module never sees a layout that only
    one topology can express). mx.zero (parallel/zero.py) rides this
    end to end: a zero'd trainer's manifests record the per-shard
    opt-state layouts, and a restore replans them bit-exactly onto a
    different mesh, onto the unsharded layout, or off it — zero on/off
    is a reshardable fingerprint key, not a mismatch.

Three surfaces:

  * **layout description** — `state_layouts(trainer)` records one entry
    per checkpointed array (name, global shape, dtype, PartitionSpec
    tree, mesh axis sizes). `mx.resilience.write_checkpoint` stores the
    list in the manifest (`"shardings"`), so a later restore can plan the
    redistribution from metadata alone, before touching any payload.
  * **planning** — `plan_restore(manifest, trainer)` matches the
    checkpoint's recorded layouts against the restoring trainer's and
    classifies every array move (`aligned` / `split` / `merge` /
    `replicate` / `redistribute`), with byte and per-array peak-memory
    accounting. Global-shape disagreement raises `ReshardError` up
    front: resharding changes layout, never shape.
  * **execution** — `Session.redistribute(arr, dst_sharding)` moves one
    live array. The device path is a planned `jax.device_put` (XLA emits
    the minimal portable collective for the src→dst pair); the host path
    gathers the array ONCE on the host by assembling addressable shards
    (per-shard D2H copies, replicated shards copied once — never a
    device-side all-gather) and scatters per-device slices via
    `make_array_from_callback` — the fallback for degenerate topologies
    where no live collective can run. Arrays are processed one at a
    time, so peak memory during a whole-trainer reshard is bounded by
    the LARGEST array, not the model.

The checkpoint-restore path needs no executor at all: orbax reads each
target shard's byte range directly from disk — inherently the
gather/scatter schedule with the source mesh not even required to exist.
There, this module contributes the gate (mesh mismatch → planned reshard
instead of MeshMismatchError while the `reshard` knob allows it), the
plan, and the telemetry (reshard_seconds / reshard_bytes_total /
reshard_peak_bytes, a "reshard" event, and the post-mortem topology
transition). Live in-process resizes (`parallel.elastic.resize_trainer`)
use the executor directly.
"""
from __future__ import annotations

import sys
import time

import numpy as np

from .. import config as _config
from .. import goodput as _goodput
from .. import telemetry as _telemetry

__all__ = ["ReshardError", "Plan", "Session", "state_layouts",
           "describe_array", "plan_restore", "plan_arrays", "redistribute",
           "classify_move", "last_reshard"]

_M_SECONDS = _telemetry.histogram(
    "reshard_seconds", "wall time of one cross-topology redistribution "
    "(checkpoint restore onto a different mesh, or a live "
    "elastic.resize_trainer)")
_M_BYTES = _telemetry.counter(
    "reshard_bytes_total", "payload bytes redistributed across topologies, "
    "by move strategy (label strategy=): aligned moves are free, migrate "
    "re-places the same split on a new device set (shard-for-shard copy), "
    "split/merge/redistribute are bounded P2P, replicate is the last-resort "
    "all-gather (target layout itself replicated)")
_M_PEAK = _telemetry.gauge(
    "reshard_peak_bytes", "largest single-array byte count processed by the "
    "most recent redistribution — the peak-memory bound (arrays move one "
    "at a time, so the whole-model reshard never holds more than this "
    "plus the destination shard)")

#: info about the most recent reshard in this process (None before any);
#: merged into the resilience resume record so post-mortems show the
#: topology transition
_last = None


class ReshardError(RuntimeError):
    """A redistribution cannot be planned: the checkpoint's recorded
    arrays and the restoring trainer disagree on STRUCTURE (names or
    global shapes). Resharding changes layout, never shape — this is a
    different model, not a different topology."""


# ---------------------------------------------------------------------------
# layout description (what the manifest records per array)
# ---------------------------------------------------------------------------

def describe_array(name, arr):
    """One JSON-able layout record: global shape, dtype, PartitionSpec
    tree and mesh axis sizes (both None for host/single-device arrays,
    which behave as replicated)."""
    from jax.sharding import NamedSharding

    from . import specs as _specs
    from .mesh import mesh_axes

    try:
        dtype = str(np.dtype(arr.dtype))
    except TypeError:                  # extended dtypes (PRNG keys)
        dtype = str(arr.dtype)
    entry = {"name": str(name), "shape": [int(s) for s in arr.shape],
             "dtype": dtype, "spec": None, "mesh": None}
    sharding = getattr(arr, "sharding", None)
    if isinstance(sharding, NamedSharding):
        entry["spec"] = _specs.spec_to_tree(sharding.spec)
        entry["mesh"] = mesh_axes(sharding.mesh)
    return entry


def _leaf_name(path):
    """Deterministic array name from a tree_flatten_with_path key path:
    "params/0", "opt_state/1/0", "rng_key"."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def state_layouts(trainer):
    """Layout records for every leaf of the trainer's checkpointed state
    pytree (the same `_state_pytree()` save and restore use, so names can
    never drift from what orbax writes)."""
    import jax.tree_util as jtu

    state = trainer._state_pytree()
    leaves, _ = jtu.tree_flatten_with_path(state)
    return [describe_array(_leaf_name(path), leaf)
            for path, leaf in leaves]


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

def _dim_counts(shape, spec_tree, mesh):
    """Per-dim shard counts for a layout record: dim i splits into
    prod(mesh[axis]) pieces over the axes its spec entry names."""
    counts = []
    mesh = mesh or {}
    spec_tree = spec_tree or []
    for i in range(len(shape)):
        entry = spec_tree[i] if i < len(spec_tree) else None
        if entry is None:
            counts.append(1)
            continue
        axes = [entry] if isinstance(entry, str) else list(entry)
        n = 1
        for a in axes:
            n *= int(mesh.get(a, 1))
        counts.append(max(1, n))
    return counts


def classify_move(src_counts, dst_counts):
    """Name the redistribution one array needs, from per-dim shard
    counts:

      aligned      — same split; local shard reads, zero movement
      split        — every dst shard is a slice of one src shard
                     (refinement: mesh grew / axis subdivided)
      merge        — every dst shard concatenates whole src shards
                     (coarsening: mesh shrank)
      replicate    — the TARGET layout is replicated while the source is
                     sharded: the one legitimate all-gather (last resort,
                     and an endpoint, never an intermediate)
      redistribute — the split moved to different dims (data↔model axis
                     change): bounded P2P chunk exchange

    Counts alone cannot see a DEVICE-SET change: the call sites upgrade
    "aligned" to "migrate" (same split, different devices/mesh — the
    payload is copied shard-for-shard, so its bytes count as moved) when
    the shardings or recorded meshes differ.
    """
    if src_counts == dst_counts:
        return "aligned"
    if all(d == 1 for d in dst_counts) and any(s > 1 for s in src_counts):
        return "replicate"
    if all(d % s == 0 for s, d in zip(src_counts, dst_counts)):
        return "split"
    if all(s % d == 0 for s, d in zip(src_counts, dst_counts)):
        return "merge"
    return "redistribute"


class Plan:
    """A planned whole-state redistribution: one move per array, with
    byte and peak-memory accounting. Built from layout metadata only —
    no payload is touched until execution."""

    def __init__(self, moves):
        self.moves = list(moves)

    @property
    def bytes_total(self):
        return sum(m["bytes"] for m in self.moves)

    @property
    def bytes_moved(self):
        return sum(m["bytes"] for m in self.moves
                   if m["strategy"] != "aligned")

    @property
    def peak_bytes(self):
        """Per-array peak during execution: the largest single array's
        source-shard + destination-shard footprint (arrays are processed
        one at a time — this, not the model size, bounds memory)."""
        peak = 0
        for m in self.moves:
            peak = max(peak, m["src_shard_bytes"] + m["dst_shard_bytes"])
        return peak

    @property
    def strategies(self):
        out = {}
        for m in self.moves:
            out[m["strategy"]] = out.get(m["strategy"], 0) + 1
        return out

    def bytes_by_strategy(self):
        out = {}
        for m in self.moves:
            out[m["strategy"]] = out.get(m["strategy"], 0) + m["bytes"]
        return out

    def describe(self):
        strat = ", ".join(f"{v} {k}" for k, v in sorted(self.strategies.items()))
        return (f"{len(self.moves)} arrays, "
                f"{self.bytes_total / 1e6:.1f} MB total "
                f"({self.bytes_moved / 1e6:.1f} MB redistributed: {strat}); "
                f"peak per-array {self.peak_bytes / 1e6:.1f} MB")


def _dtype_itemsize(name):
    try:
        return np.dtype(name).itemsize
    except TypeError:
        return 4        # jax PRNG key dtypes and other extended dtypes


def plan_arrays(src_layouts, dst_layouts):
    """Plan src→dst for two layout lists (matched by name). Raises
    ReshardError when the structures disagree — different names, counts,
    or global shapes mean a different MODEL, which no redistribution can
    fix."""
    src_by_name = {e["name"]: e for e in src_layouts}
    dst_by_name = {e["name"]: e for e in dst_layouts}
    missing = sorted(set(dst_by_name) - set(src_by_name))
    extra = sorted(set(src_by_name) - set(dst_by_name))
    if missing or extra:
        raise ReshardError(
            "checkpoint and trainer state structures differ — this is a "
            f"different model, not a different topology (checkpoint lacks "
            f"{missing[:5]}, has extra {extra[:5]})")
    moves = []
    for name in sorted(dst_by_name):
        src, dst = src_by_name[name], dst_by_name[name]
        if list(src["shape"]) != list(dst["shape"]):
            raise ReshardError(
                f"array {name!r}: checkpoint global shape "
                f"{tuple(src['shape'])} != trainer {tuple(dst['shape'])} — "
                "resharding changes layout, never shape")
        shape = tuple(dst["shape"])
        nbytes = int(np.prod(shape)) * _dtype_itemsize(dst["dtype"]) \
            if shape else _dtype_itemsize(dst["dtype"])
        s_counts = _dim_counts(shape, src.get("spec"), src.get("mesh"))
        d_counts = _dim_counts(shape, dst.get("spec"), dst.get("mesh"))
        strategy = classify_move(s_counts, d_counts)
        if strategy == "aligned" and \
                (src.get("mesh") or {}) != (dst.get("mesh") or {}):
            # same split on a DIFFERENT mesh: every shard is re-read onto
            # a new device — movement, not a free local read
            strategy = "migrate"
        s_parts = int(np.prod(s_counts)) if s_counts else 1
        d_parts = int(np.prod(d_counts)) if d_counts else 1
        moves.append({
            "name": name, "shape": list(shape), "bytes": nbytes,
            "strategy": strategy,
            "src_shard_bytes": nbytes // max(1, s_parts),
            "dst_shard_bytes": nbytes // max(1, d_parts),
        })
    return Plan(moves)


def plan_restore(manifest, trainer):
    """Plan restoring a manifest's recorded state onto `trainer`'s
    current placement. Checkpoints from before per-array shardings were
    recorded (no "shardings" in the manifest) get a coarse plan: every
    array marked `redistribute`, bytes from the trainer side."""
    dst = state_layouts(trainer)
    src = manifest.get("shardings")
    if not src:
        moves = []
        for e in dst:
            shape = tuple(e["shape"])
            nbytes = int(np.prod(shape)) * _dtype_itemsize(e["dtype"]) \
                if shape else _dtype_itemsize(e["dtype"])
            d_counts = _dim_counts(shape, e.get("spec"), e.get("mesh"))
            d_parts = int(np.prod(d_counts)) if d_counts else 1
            moves.append({"name": e["name"], "shape": list(shape),
                          "bytes": nbytes, "strategy": "redistribute",
                          "src_shard_bytes": nbytes,
                          "dst_shard_bytes": nbytes // max(1, d_parts)})
        return Plan(moves)
    return plan_arrays(src, dst)


# ---------------------------------------------------------------------------
# execution (live arrays: elastic resize; checkpoint restores go via orbax)
# ---------------------------------------------------------------------------

def _live_counts(arr, sharding):
    from jax.sharding import NamedSharding

    from . import specs as _specs
    from .mesh import mesh_axes
    if not isinstance(sharding, NamedSharding):
        return [1] * arr.ndim
    return _dim_counts(arr.shape, _specs.spec_to_tree(sharding.spec),
                       mesh_axes(sharding.mesh))


def _host_gather(arr):
    """Assemble the global array on the host from addressable shards —
    per-shard D2H copies only (each replicated index copied once), never
    a device-side all-gather. Peak host memory: this one array.

    Requires a fully addressable array: on a multi-process gang each
    process sees only its own shards, so a per-process host gather would
    silently fill the other hosts' regions with uninitialized memory —
    cross-host redistribution goes through the checkpoint path instead
    (orbax reads every target shard from the shared filesystem)."""
    if not getattr(arr, "is_fully_addressable", True):
        raise ReshardError(
            "host gather/scatter needs a fully addressable array; this "
            "process holds only its local shards. Redistribute across "
            "hosts via a checkpoint (save_states + load_states with "
            "reshard='auto') instead of a live host-path move.")
    out = np.empty(arr.shape, np.dtype(arr.dtype))
    seen = set()
    for sh in arr.addressable_shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in sh.index) \
            if sh.index else ()
        if key in seen:
            continue
        seen.add(key)
        out[sh.index] = np.asarray(sh.data)
    return out


def _host_scatter(host, dst_sharding):
    """Place a host array under `dst_sharding`, each device receiving
    exactly its slice (no device ever holds more than its shard)."""
    import jax
    return jax.make_array_from_callback(
        host.shape, dst_sharding, lambda idx: host[idx])


class Session:
    """One redistribution session: moves arrays one at a time (bounding
    peak memory at the largest array), tracks bytes/strategy/peak, and
    emits the telemetry + diagnostics record at finish().

    via: "auto" (device collectives, host fallback), "host" (force the
    gather/scatter path — for degenerate topologies where the source and
    target meshes cannot run a collective together), or None to read the
    `reshard` knob ("off" behaves as "auto" here: gating happens at the
    restore call site, not mid-move)."""

    def __init__(self, via=None, chunk_bytes=None):
        mode = via or _config.get("reshard")
        self.via = mode if mode in ("host",) else "auto"
        self.chunk_bytes = int(chunk_bytes if chunk_bytes is not None
                               else _config.get("reshard_chunk_bytes"))
        self.moves = []
        self._t0 = time.perf_counter()

    # ------------------------------------------------------------- move
    def redistribute(self, arr, dst_sharding):
        """Move one array to `dst_sharding`. Device path: a planned
        jax.device_put (XLA's portable src→dst collective). Host path:
        gather-once/scatter-slices. Auto prefers the device path but
        routes `merge`/`redistribute` moves of arrays above
        reshard_chunk_bytes through the host (their device schedule may
        materialize a gathered intermediate; the host path's peak is one
        host copy + one device shard)."""
        import jax

        nbytes = int(arr.size) * _dtype_itemsize(arr.dtype)
        src_sharding = getattr(arr, "sharding", None)
        if src_sharding == dst_sharding:
            self._note("aligned", arr, nbytes, src_sharding, dst_sharding)
            return arr
        s_counts = _live_counts(arr, src_sharding)
        d_counts = _live_counts(arr, dst_sharding)
        strategy = classify_move(s_counts, d_counts)
        if strategy == "aligned":
            # shardings already compared unequal above: same split on a
            # different device set — a shard-for-shard copy (migrate)
            strategy = "migrate"
        # auto prefers the host path only for arrays it can actually
        # assemble (fully addressable); an EXPLICIT via='host' on a
        # multi-process array raises in _host_gather rather than
        # corrupting silently
        use_host = self.via == "host" or (
            strategy in ("merge", "redistribute")
            and nbytes > self.chunk_bytes
            and getattr(arr, "is_fully_addressable", True))
        if not use_host:
            try:
                out = jax.device_put(arr, dst_sharding)
            except Exception as e:     # noqa: BLE001 — degenerate topology
                print(f"mx.reshard: device path failed ({type(e).__name__}:"
                      f" {e}) — falling back to host gather/scatter",
                      file=sys.stderr)
                use_host = True
        if use_host:
            out = _host_scatter(_host_gather(arr), dst_sharding)
        self._note(strategy, arr, nbytes, src_sharding, dst_sharding)
        return out

    def _note(self, strategy, arr, nbytes, src_sharding, dst_sharding):
        s_parts = int(np.prod(_live_counts(arr, src_sharding)))
        d_parts = int(np.prod(_live_counts(arr, dst_sharding)))
        self.moves.append({
            "name": f"array{len(self.moves)}", "shape": list(arr.shape),
            "bytes": nbytes, "strategy": strategy,
            "src_shard_bytes": nbytes // max(1, s_parts),
            "dst_shard_bytes": nbytes // max(1, d_parts)})

    # ----------------------------------------------------------- finish
    def finish(self, kind, src_fp=None, dst_fp=None):
        """Emit the session's record: telemetry counters/histogram/gauge,
        a "reshard" event, the diagnostics ring entry, and the module's
        last_reshard() info (merged into the resume post-mortem)."""
        plan = Plan(self.moves)
        t1 = time.perf_counter()
        note_reshard(kind, plan, t1 - self._t0,
                     src_fp=src_fp, dst_fp=dst_fp)
        if _goodput._enabled:
            # "op" not "kind": the record's "kind" key is the line type
            _goodput.note("reshard", self._t0, t1, op=kind)
        return plan


def redistribute(arr, dst_sharding, via=None):
    """One-shot module-level convenience (no session record)."""
    return Session(via=via).redistribute(arr, dst_sharding)


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def note_reshard(kind, plan, seconds, src_fp=None, dst_fp=None):
    """Record one completed redistribution (kind: "restore" for the
    checkpoint path, "resize" for a live elastic resize)."""
    global _last
    info = {"op": kind, "arrays": len(plan.moves),
            "bytes_total": plan.bytes_total,
            "bytes_moved": plan.bytes_moved,
            "peak_bytes": plan.peak_bytes,
            "strategies": plan.strategies,
            "seconds": round(float(seconds), 6),
            "from": src_fp, "to": dst_fp}
    _last = info
    try:
        from .. import resilience as _resilience
        _resilience._pending_reshard = dict(info)
    except Exception:
        pass
    # stderr, like every operational message here and in resilience: a
    # worker's stdout may be machine-parsed (bench JSON, loss scraping)
    print(f"mx.reshard: {kind} across topologies "
          f"({_fp_brief(src_fp)} -> {_fp_brief(dst_fp)}): {plan.describe()} "
          f"in {seconds:.3f}s", file=sys.stderr)
    if _telemetry._enabled:
        _M_SECONDS.observe(float(seconds))
        for strategy, nbytes in plan.bytes_by_strategy().items():
            _M_BYTES.labels(strategy=strategy).inc(nbytes)
        _M_PEAK.set(plan.peak_bytes)
        _telemetry.event("reshard", **info)
    try:
        from .. import diagnostics as _diagnostics
        _diagnostics.record_event("reshard", **info)
    except Exception:
        pass
    return info


def _fp_brief(fp):
    if not isinstance(fp, dict):
        return "?"
    mesh = fp.get("mesh_shape")
    mode = fp.get("param_mode")
    parts = []
    if mesh:
        parts.append("x".join(f"{k}={v}" for k, v in sorted(mesh.items())
                              if v != 1) or "1-device")
    if mode:
        parts.append(str(mode))
    return "/".join(parts) or "?"


def last_reshard():
    """Info dict of the most recent redistribution in this process (None
    before any) — surfaced in the post-mortem resume section."""
    return dict(_last) if _last else None
