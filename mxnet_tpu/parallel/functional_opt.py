"""Functional optimizer wrappers for jitted train steps.

Bridges the stateful `mxnet_tpu.optimizer.Optimizer` API to pure
(params, grads, state, t, lr) -> (new_params, new_state) updates usable under
`jax.jit` on a sharded mesh. With fsdp param sharding this realizes
weight-update sharding (PAPERS.md: Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training): each device updates only its shard.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops as _ops
from .. import optimizer as opt_mod

__all__ = ["FunctionalOptimizer"]


class FunctionalOptimizer:
    """Pure-update view of an Optimizer instance (sgd/nag/adam/adamw/lamb)."""

    def __init__(self, optimizer, param_names=None):
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self.opt = optimizer
        self.kind = type(optimizer).__name__.lower()
        if self.kind not in ("sgd", "nag", "adam", "adamw", "lamb"):
            raise NotImplementedError(
                f"functional path for optimizer '{self.kind}' not implemented; "
                "use the eager Trainer")
        self.param_names = param_names

    # -- state ----------------------------------------------------------
    def init(self, params):
        states = []
        for p in params:
            if self.kind in ("adam", "adamw", "lamb"):
                # distinct buffers: they are donated independently each step
                states.append((jnp.zeros(p.shape, jnp.float32),
                               jnp.zeros(p.shape, jnp.float32)))
            elif self.kind in ("sgd", "nag") and getattr(self.opt, "momentum", 0):
                states.append((jnp.zeros(p.shape, jnp.float32),))
            else:
                states.append(())
        return states

    # -- update ---------------------------------------------------------
    def apply(self, params, grads, states, t, lr):
        """t, lr: traced scalars (t for bias correction; lr from scheduler)."""
        o = self.opt
        clip = o.clip_gradient if o.clip_gradient else -1.0
        new_params, new_states = [], []
        for i, (p, g, s) in enumerate(zip(params, grads, states)):
            wd = o.wd
            if self.kind == "sgd":
                if s:
                    w, m = _ops.OPS["sgd_mom_update"](
                        p, g, s[0], lr, momentum=o.momentum, wd=wd,
                        rescale_grad=o.rescale_grad, clip_gradient=clip)
                    new_states.append((m,))
                else:
                    w = _ops.OPS["sgd_update"](
                        p, g, lr, wd=wd, rescale_grad=o.rescale_grad,
                        clip_gradient=clip)
                    new_states.append(())
            elif self.kind == "nag":
                w, m = _ops.OPS["nag_mom_update"](
                    p, g, s[0], lr, momentum=o.momentum, wd=wd,
                    rescale_grad=o.rescale_grad, clip_gradient=clip)
                new_states.append((m,))
            elif self.kind in ("adam", "adamw"):
                # bias-corrected lr (matches the stateful Adam.update)
                lr_t = lr * jnp.sqrt(1 - o.beta2 ** t) / (1 - o.beta1 ** t)
                op = "adam_update" if self.kind == "adam" else "adamw_update"
                w, m, v = _ops.OPS[op](
                    p, g, s[0], s[1], lr_t, beta1=o.beta1, beta2=o.beta2,
                    epsilon=o.epsilon, wd=wd, rescale_grad=o.rescale_grad,
                    clip_gradient=clip)
                new_states.append((m, v))
            elif self.kind == "lamb":
                w, m, v = _ops.OPS["lamb_update"](
                    p, g, s[0], s[1], lr, beta1=o.beta1, beta2=o.beta2,
                    epsilon=o.epsilon, t=t, bias_correction=o.bias_correction,
                    wd=self._wd_for(i), rescale_grad=o.rescale_grad,
                    clip_gradient=clip, lower_bound=o.lower_bound,
                    upper_bound=o.upper_bound)
                new_states.append((m, v))
            new_params.append(w)
        return new_params, new_states

    def _wd_for(self, i):
        """LAMB convention: no weight decay on bias/LayerNorm params."""
        if self.param_names is None:
            return self.opt.wd
        name = self.param_names[i]
        if name.endswith("bias") or name.endswith("beta") or name.endswith("gamma"):
            return 0.0
        return self.opt.wd

    def lr_at(self, num_update):
        o = self.opt
        return o.lr_scheduler(num_update) if o.lr_scheduler else o.lr
