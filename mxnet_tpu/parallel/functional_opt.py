"""Functional optimizer wrappers for jitted train steps.

Bridges the stateful `mxnet_tpu.optimizer.Optimizer` API to pure
(params, grads, state, t, lr) -> (new_params, new_state) updates usable under
`jax.jit` on a sharded mesh. With fsdp param sharding this realizes
weight-update sharding (PAPERS.md: Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training): each device updates only its shard.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops as _ops
from .. import optimizer as opt_mod

__all__ = ["FunctionalOptimizer"]


class FunctionalOptimizer:
    """Pure-update view of an Optimizer instance (sgd/nag/adam/adamw/lamb)."""

    def __init__(self, optimizer, param_names=None):
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer)
        self.opt = optimizer
        self.kind = type(optimizer).__name__.lower()
        if self.kind not in ("sgd", "nag", "adam", "adamw", "lamb"):
            raise NotImplementedError(
                f"functional path for optimizer '{self.kind}' not implemented; "
                "use the eager Trainer")
        self.param_names = param_names

    # -- state ----------------------------------------------------------
    def init(self, params):
        states = []
        for p in params:
            if self.kind in ("adam", "adamw", "lamb"):
                # distinct buffers: they are donated independently each step
                states.append((jnp.zeros(p.shape, jnp.float32),
                               jnp.zeros(p.shape, jnp.float32)))
            elif self.kind in ("sgd", "nag") and getattr(self.opt, "momentum", 0):
                states.append((jnp.zeros(p.shape, jnp.float32),))
            else:
                states.append(())
        return states

    # -- update ---------------------------------------------------------
    def apply(self, params, grads, states, t, lr):
        """t, lr: traced scalars (t for bias correction; lr from scheduler)."""
        o = self.opt
        clip = o.clip_gradient if o.clip_gradient else -1.0
        new_params, new_states = [], []
        for i, (p, g, s) in enumerate(zip(params, grads, states)):
            wd = o.wd
            if self.kind == "sgd":
                if s:
                    w, m = _ops.OPS["sgd_mom_update"](
                        p, g, s[0], lr, momentum=o.momentum, wd=wd,
                        rescale_grad=o.rescale_grad, clip_gradient=clip)
                    new_states.append((m,))
                else:
                    w = _ops.OPS["sgd_update"](
                        p, g, lr, wd=wd, rescale_grad=o.rescale_grad,
                        clip_gradient=clip)
                    new_states.append(())
            elif self.kind == "nag":
                w, m = _ops.OPS["nag_mom_update"](
                    p, g, s[0], lr, momentum=o.momentum, wd=wd,
                    rescale_grad=o.rescale_grad, clip_gradient=clip)
                new_states.append((m,))
            elif self.kind in ("adam", "adamw"):
                # bias-corrected lr (matches the stateful Adam.update)
                lr_t = lr * jnp.sqrt(1 - o.beta2 ** t) / (1 - o.beta1 ** t)
                # mx.kernels: one fused VMEM pass over w/g/m/v instead of
                # the elementwise HLO chain (pallas_ops/fused_update.py;
                # adam_update falls back to the exact _ops lowering
                # unless the kernel is engaged — trace-time decision, so
                # kernels=off steps are byte-identical)
                from ..pallas_ops import fused_update as _fu
                w, m, v = _fu.adam_update(
                    p, g, s[0], s[1], lr_t, beta1=o.beta1, beta2=o.beta2,
                    epsilon=o.epsilon, wd=wd,
                    rescale_grad=o.rescale_grad, clip_gradient=clip,
                    decoupled_wd=self.kind == "adamw")
                new_states.append((m, v))
            elif self.kind == "lamb":
                w, m, v = _ops.OPS["lamb_update"](
                    p, g, s[0], s[1], lr, beta1=o.beta1, beta2=o.beta2,
                    epsilon=o.epsilon, t=t, bias_correction=o.bias_correction,
                    wd=self._wd_for(i), rescale_grad=o.rescale_grad,
                    clip_gradient=clip, lower_bound=o.lower_bound,
                    upper_bound=o.upper_bound)
                new_states.append((m, v))
            new_params.append(w)
        return new_params, new_states

    def _wd_for(self, i):
        """LAMB convention: no weight decay on bias/LayerNorm params."""
        if self.param_names is None:
            return self.opt.wd
        name = self.param_names[i]
        if name.endswith("bias") or name.endswith("beta") or name.endswith("gamma"):
            return 0.0
        return self.opt.wd

    def lr_at(self, num_update):
        o = self.opt
        return o.lr_scheduler(num_update) if o.lr_scheduler else o.lr

    def lr_traced(self):
        """A jit-traceable `f(t) -> lr` for the attached schedule, or None
        when it cannot be expressed (a custom LRScheduler subclass).

        When this returns a function, the sharded step computes lr from the
        device-resident step counter INSIDE the jitted step — removing the
        two per-step host->device scalar transfers (t, lr) the host-side
        `lr_at` path pays. A constant-lr optimizer returns a closure over
        the current `o.lr`; the step cache keys on that value so
        `set_learning_rate` mid-run still takes effect (one warm re-jit
        instead of a transfer every step)."""
        from .. import lr_scheduler as _lrs
        o = self.opt
        sch = o.lr_scheduler
        if sch is None:
            base = float(o.lr)
            return lambda t: jnp.float32(base)
        # exact types only: a subclass may override __call__ with
        # arbitrary host logic that would mistrace under jit
        if type(sch) is _lrs.FactorScheduler:
            def main(t):
                lr = sch.base_lr * sch.factor ** jnp.floor(t / sch.step)
                return jnp.maximum(lr, sch.stop_factor_lr)
        elif type(sch) is _lrs.MultiFactorScheduler:
            steps = jnp.asarray(sch.step, jnp.float32)
            def main(t):
                return sch.base_lr * sch.factor ** jnp.sum(t >= steps)
        elif type(sch) is _lrs.PolyScheduler:
            def main(t):
                frac = jnp.clip((t - sch.warmup_steps)
                                / max(sch.max_steps, 1), 0.0, 1.0)
                return sch.final_lr + (sch.base_lr - sch.final_lr) \
                    * (1.0 - frac) ** sch.power
        elif type(sch) is _lrs.CosineScheduler:
            def main(t):
                frac = jnp.clip((t - sch.warmup_steps)
                                / max(sch.max_steps, 1), 0.0, 1.0)
                return sch.final_lr + (sch.base_lr - sch.final_lr) \
                    * (1.0 + jnp.cos(jnp.pi * frac)) / 2.0
        else:
            return None
        if not sch.warmup_steps:
            return lambda t: jnp.float32(main(t))
        span = sch.warmup_final_lr - sch.warmup_begin_lr
        if sch.warmup_mode == "linear":
            def warm(t):
                return sch.warmup_begin_lr + span * t / sch.warmup_steps
        else:
            def warm(t):
                return sch.warmup_begin_lr + span * (
                    1.0 - jnp.exp(-t / max(sch.warmup_steps, 1)))
        return lambda t: jnp.float32(
            jnp.where(t < sch.warmup_steps, warm(t), main(t)))
