"""Fused multi-tensor LAMB with f32 master weights (reference:
`src/operator/optimizer_op.cc` `multi_lamb_update` / `multi_mp_lamb_update` —
one kernel over all parameters instead of one launch per tensor, plus the
`mp_*` master-copy discipline).

TPU-first design: the master weights and both moment buffers live as ONE
flat f32 vector each, with segments padded to a lane-aligned chunk. The
jitted train step unflattens the master into per-tensor model-dtype views
(slice+reshape+cast, which XLA fuses into consumers), so autodiff delivers
the gradient already FLAT — no per-step repacking. Per-parameter L2 norms
(LAMB trust ratios) reduce as a dense (rows, chunk) row-sum followed by a
cumsum + boundary-gather — no scatter/segment_sum anywhere, which XLA
lowers poorly on TPU. The elementwise phase then runs as two fused passes
over contiguous memory instead of ~200 little kernels with 2 reductions
each.

Integration: ShardedTrainer uses this path for LAMB in 'replicate' param
mode (single-chip / dp meshes). Under fsdp/tp sharding the flat concat
would force cross-shard reshards, so the per-parameter path (which shards
cleanly) is kept there.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["FusedLamb"]

_CHUNK = 512  # lane-aligned segment padding


class FusedLamb:
    """Precomputed flat layout + the two-pass fused LAMB update."""

    def __init__(self, shapes, dtypes, wds, beta1, beta2, epsilon,
                 bias_correction, rescale_grad, clip_gradient,
                 lower_bound, upper_bound, moments_dtype=jnp.float32):
        self.shapes = [tuple(s) for s in shapes]
        self.dtypes = list(dtypes)
        self.moments_dtype = jnp.dtype(moments_dtype)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.bias_correction = bias_correction
        self.rescale = rescale_grad
        self.clip = clip_gradient
        self.lo, self.hi = lower_bound, upper_bound

        sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        padded = [(n + _CHUNK - 1) // _CHUNK * _CHUNK for n in sizes]
        self.sizes = sizes
        self.offsets = np.cumsum([0] + padded).tolist()
        self.total = self.offsets[-1]
        self.n_rows = self.total // _CHUNK
        # row r belongs to segment row_seg[r]; segments are whole row ranges
        row_seg = np.zeros(self.n_rows, np.int32)
        for i, (off, pad) in enumerate(zip(self.offsets[:-1], padded)):
            row_seg[off // _CHUNK: (off + pad) // _CHUNK] = i
        self._row_seg = jnp.asarray(row_seg)
        self._wd_seg = jnp.asarray(np.asarray(wds, np.float32))

    # -- flat <-> per-param ---------------------------------------------
    def flatten(self, arrs, dtype=jnp.float32):
        parts = []
        for a, n, s in zip(arrs, self.sizes, self.shapes):
            flat = jnp.ravel(a).astype(dtype)
            pad = (n + _CHUNK - 1) // _CHUNK * _CHUNK - n
            if pad:
                flat = jnp.concatenate([flat, jnp.zeros(pad, dtype)])
            parts.append(flat)
        return jnp.concatenate(parts) if parts else jnp.zeros(0, dtype)

    def unflatten(self, flat):
        """Per-tensor model-dtype views of the flat master. Differentiable:
        the vjp scatters per-tensor cotangents back into a flat vector, so
        `jax.grad` of a loss over `unflatten(master)` yields flat grads."""
        outs = []
        for off, n, shape, dt in zip(self.offsets[:-1], self.sizes,
                                     self.shapes, self.dtypes):
            outs.append(flat[off:off + n].reshape(shape).astype(dt))
        return outs

    def unflatten_master(self, flat):
        """Per-tensor f32 views WITHOUT the model-dtype cast — the canonical
        (mode-portable) checkpoint layout for master weights and moments."""
        return [flat[off:off + n].reshape(shape)
                for off, n, shape in zip(self.offsets[:-1], self.sizes,
                                         self.shapes)]

    def shardable_rows(self, extent):
        """True when the flat (n_rows, CHUNK) layout splits into WHOLE
        rows across `extent` devices — the divisibility mx.zero's flat
        master/moment sharding requires. Each device then owns complete
        512-lane rows, so apply_flat's row-wise math (per-row moment/
        update passes, the (R, 1) broadcasts) partitions without any
        cross-shard reads; only the tiny per-segment norm scatter-adds
        reduce across shards. A non-divisible layout falls back to the
        replicated master (parallel/zero.flat_spec returns None)."""
        extent = int(extent)
        return extent >= 1 and self.n_rows >= extent \
            and self.n_rows % extent == 0

    # -- the fused step --------------------------------------------------
    def apply_flat(self, w, g, m, v, t, lr):
        """w/m/v: flat f32 state (padded layout); g: flat f32 grads.
        Returns (new_w, new_m, new_v).

        HBM-traffic-minimal formulation (measured ~3x faster than the naive
        one at BERT-base scale): everything runs on (n_rows, CHUNK) 2D
        views so per-segment scalars broadcast as (rows, 1) — never
        materialized full-size via repeat — and the row-norm reductions
        fuse into the same pass that produces the update. Padding lanes
        need no masking: w/m/v padding is zero by construction and grad
        padding is zero (flatten pads zeros; the unflatten vjp only
        scatters real elements), so every derived quantity is zero there
        too.

        mx.kernels: when the fused-update Pallas kernels are engaged
        (`kernels` knob + TPU/interpreter + single-device step — see
        pallas_ops/fused_update.py), the two elementwise passes run as
        Pallas kernels over the same (rows, CHUNK) views; the tiny
        per-segment norm scatter and trust ratio stay in XLA. With
        kernels=off this method is byte-identical to the pre-kernel
        build."""
        from ..pallas_ops import fused_update as _fu
        if _fu.engaged(self.total):
            return self._apply_flat_pallas(w, g, m, v, t, lr)
        R, C = self.n_rows, _CHUNK
        W = w.reshape(R, C)
        G = g.reshape(R, C) * self.rescale
        if self.clip and self.clip > 0:
            G = jnp.clip(G, -self.clip, self.clip)
        mdt = self.moments_dtype
        new_m = self.b1 * m.reshape(R, C).astype(jnp.float32) \
            + (1 - self.b1) * G
        new_v = self.b2 * v.reshape(R, C).astype(jnp.float32) \
            + (1 - self.b2) * jnp.square(G)
        if mdt != jnp.float32:
            # reduced-precision moment storage (config `lamb_moments_dtype`):
            # ~30% less optimizer HBM traffic at BERT scale.  Round-trip
            # through the storage dtype BEFORE the trust-ratio norms so the
            # norm, the applied update, and the carried state all see the
            # SAME values — trust stays consistent with what is stored.
            new_m = new_m.astype(mdt).astype(jnp.float32)
            new_v = new_v.astype(mdt).astype(jnp.float32)
        wd_rows = jnp.take(self._wd_seg, self._row_seg)[:, None]  # (R, 1)

        def make_update(mm, vv, ww):
            m_hat, v_hat = mm, vv
            if self.bias_correction:
                m_hat = mm / (1 - self.b1 ** t)
                v_hat = vv / (1 - self.b2 ** t)
            return m_hat / (jnp.sqrt(v_hat) + self.eps) + wd_rows * ww

        def seg_norm(rows_sq):
            # rows_sq: (R,) per-row sum of squares. Segment-level
            # scatter-add, NOT a global cumsum difference: with ~1e8-scale
            # prefixes an f32 cumsum loses every small segment (LayerNorm
            # beta sum-of-squares ~1e-2) to cancellation. The scatter is
            # over n_rows elements only (total/512), off the hot path.
            segsum = jnp.zeros(len(self.sizes), jnp.float32).at[
                self._row_seg].add(rows_sq)
            return jnp.sqrt(segsum)

        # pass 1: `update` here feeds ONLY the norm reductions, so XLA fuses
        # it into them — it is never written to HBM (at BERT-base that
        # temporary is a ~0.5 GB round-trip; memory_analysis confirms a
        # full-size 355 MB temp without the barrier below, 12 MB with)
        r1 = seg_norm(jnp.sum(jnp.square(W), axis=1))
        r2 = seg_norm(jnp.sum(jnp.square(make_update(new_m, new_v, W)),
                              axis=1))
        # identical semantics to lamb_update_phase2: zero norms are replaced
        # by 1 BEFORE the ratio, so a zero-init param gets trust = 1/||u||
        r1 = jnp.where(r1 > 0, r1, 1.0)
        r2 = jnp.where(r2 > 0, r2, 1.0)
        trust = r1 / r2
        if self.lo and self.lo > 0:
            trust = jnp.maximum(trust, self.lo)
        if self.hi and self.hi > 0:
            trust = jnp.minimum(trust, self.hi)
        trust_rows = jnp.take(trust, self._row_seg)[:, None]      # (R, 1)
        # pass 2: RECOMPUTE the update from barriered inputs instead of
        # reusing pass 1's value — the barrier defeats CSE (which would
        # merge the two expressions back into one materialized temporary);
        # the recompute is pure FLOPs, traded for a full HBM round-trip
        new_m = new_m.astype(mdt)
        new_v = new_v.astype(mdt)
        Wb, mb, vb = jax.lax.optimization_barrier((W, new_m, new_v))
        new_w = Wb - lr * trust_rows * make_update(
            mb.astype(jnp.float32), vb.astype(jnp.float32), Wb)
        return (new_w.reshape(-1), new_m.reshape(-1), new_v.reshape(-1))

    def _apply_flat_pallas(self, w, g, m, v, t, lr):
        """The same update via the mx.kernels fused-update passes: pass 1
        (moments + per-row sums of squares) and pass 2 (trust-scaled
        apply) each run once over VMEM-resident tiles; only the
        per-segment norm scatter + trust ratio (n_segments elements)
        execute as XLA ops between them — the two-kernel split realizes
        the optimization_barrier structure physically."""
        from ..pallas_ops import fused_update as _fu
        R, C = self.n_rows, _CHUNK
        W = w.reshape(R, C)
        G = g.reshape(R, C)
        c1 = (1 - self.b1 ** t) if self.bias_correction else 1.0
        c2 = (1 - self.b2 ** t) if self.bias_correction else 1.0
        wd_rows = jnp.take(self._wd_seg, self._row_seg)
        new_m, new_v, rw, ru = _fu.lamb_pass1(
            W, G, m, v, wd_rows, c1, c2, beta1=self.b1, beta2=self.b2,
            epsilon=self.eps, rescale_grad=self.rescale,
            clip_gradient=self.clip, bias_correction=self.bias_correction,
            moments_dtype=self.moments_dtype)

        def seg_norm(rows_sq):
            # identical to the XLA path: segment scatter-add, not a
            # cumsum difference (f32 cancellation on ~1e8 prefixes)
            segsum = jnp.zeros(len(self.sizes), jnp.float32).at[
                self._row_seg].add(rows_sq)
            return jnp.sqrt(segsum)

        r1 = seg_norm(rw)
        r2 = seg_norm(ru)
        r1 = jnp.where(r1 > 0, r1, 1.0)
        r2 = jnp.where(r2 > 0, r2, 1.0)
        trust = r1 / r2
        if self.lo and self.lo > 0:
            trust = jnp.maximum(trust, self.lo)
        if self.hi and self.hi > 0:
            trust = jnp.minimum(trust, self.hi)
        trust_rows = jnp.take(trust, self._row_seg)
        new_w = _fu.lamb_pass2(
            W, new_m, new_v, wd_rows, trust_rows, c1, c2, lr,
            beta1=self.b1, beta2=self.b2, epsilon=self.eps,
            bias_correction=self.bias_correction)
        # pass 1 hands its moments to pass 2 still row-padded (no
        # pad(slice(x)) HBM round-trip between the passes); only the
        # carried state slices back to the flat layout
        return (new_w.reshape(-1), new_m[:R].reshape(-1),
                new_v[:R].reshape(-1))
