"""Expert parallelism: Switch-style mixture-of-experts over an `ep` mesh axis.

Net-new vs the reference (SURVEY.md §2.4 lists expert parallel as absent).
Mesh-TensorFlow-style dense dispatch: top-1 routing builds a one-hot
dispatch tensor, tokens travel to their expert's device via `lax.all_to_all`
(ICI), experts run batched FFN einsums on the MXU, results return through
the inverse all_to_all weighted by the router gate. Capacity-bounded so
every shape is static (XLA requirement); overflow tokens are dropped and
pass through the residual, exactly as in Switch Transformer.

Layout contract (inside shard_map over `ep`, n = axis size):
  x       (N_local, D)            tokens on this device
  router  (D, E)                  replicated
  w1      (E_local, D, F)         this device's experts
  w2      (E_local, F, D)
  E = n * E_local total experts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["moe_dispatch", "moe_route", "moe_ffn", "moe_apply"]


def moe_dispatch(x, router_w, num_experts, capacity, axis_name=None):
    """Top-1 routing: returns (dispatch, combine, aux_loss).

    dispatch (N, E, C) one-hot send tensor; combine = dispatch * gate.
    aux_loss is the Switch load-balancing loss (mean_frac · mean_prob · E).
    """
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)  # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                            # (N,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    one_hot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)
    # position of each token within its expert's capacity buffer
    pos = jnp.cumsum(one_hot, axis=0) * one_hot - 1.0              # (N, E)
    in_cap = (pos < capacity) & (one_hot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)                     # (N, E, C)
    dispatch = pos_oh * in_cap[..., None]
    combine = dispatch * gate[:, None, None]

    # load-balancing aux loss (Switch eq. 4): fraction of tokens per expert
    # times mean router prob per expert, summed, scaled by E
    frac = one_hot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux_loss = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux_loss


def moe_route(x, router_w, num_experts):
    """Compact top-1 routing: (expert (N,) int32, pos (N,) int32, gate
    (N,) f32, aux_loss). `pos` is the token's slot within its expert's
    capacity buffer; tokens beyond capacity simply carry pos >= C and
    the fused dispatch/combine kernels drop them (same semantics as
    `moe_dispatch`'s in_cap mask, without the (N, E, C) tensor)."""
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)  # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1).astype(jnp.int32)          # (N,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    one_hot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)
    # position within the expert's buffer: cumulative count of earlier
    # tokens routed to the same expert (only the chosen column is live)
    pos = (jnp.cumsum(one_hot, axis=0) * one_hot).sum(-1) \
        .astype(jnp.int32) - 1
    frac = one_hot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux_loss = num_experts * jnp.sum(frac * mean_prob)
    return expert, pos, gate, aux_loss


def _expert_ffn(buf, w1, w2, n, e_local, capacity, d_model, axis_name,
                activation):
    """The shared middle of the Switch FFN: ship each expert-shard to
    its owner, run the batched FFN einsums, ship results back. Used by
    both the einsum path and the fused-kernel path (pure code motion
    from moe_ffn — the math is unchanged)."""
    # send each expert-shard to its owner: (E, C, D) -> (n, E_local, C, D)
    buf = buf.reshape(n, e_local, capacity, d_model)
    # all_to_all over leading dim: afterwards dim 0 indexes SOURCE device,
    # and this device holds only its local experts' tokens from every peer
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    # (n, E_local, C, D): fold sources into the capacity dim for the FFN
    buf = buf.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, d_model)

    h = jnp.einsum("ecd,edf->ecf", buf, w1.astype(jnp.float32))
    h = activation(h)
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(jnp.float32))

    # route back: inverse reshape + all_to_all
    out = out.reshape(e_local, n, capacity, d_model).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    return out.reshape(n * e_local, capacity, d_model)


def moe_ffn(x, router_w, w1, w2, axis_name, capacity_factor=1.25,
            activation=jax.nn.gelu):
    """Expert-parallel Switch FFN. Call INSIDE shard_map over `axis_name`.

    Shapes per the module docstring. Returns (out (N,D), aux_loss scalar —
    already psum-averaged over the axis).

    mx.kernels: with the Pallas library engaged (`kernels` knob; safe on
    any mesh — this already runs inside shard_map) the dispatch gather
    and combine scatter run as fused kernels over compact (N,) routing
    vectors (pallas_ops/moe_kernels.py) instead of materializing the
    (N, E, C) one-hot dispatch tensor in HBM. kernels=off keeps the
    einsum formulation bit-identical to the pre-kernel build.
    """
    from ..pallas_ops import moe_kernels as _mk

    n = lax.psum(1, axis_name)
    e_local = w1.shape[0]
    num_experts = n * e_local
    n_tokens, d_model = x.shape
    capacity = max(int(n_tokens * capacity_factor / num_experts), 1)

    if _mk.engaged():
        expert, pos, gate, aux = moe_route(x, router_w, num_experts)
        buf = _mk.dispatch_to_experts(x.astype(jnp.float32), expert, pos,
                                      num_experts, capacity)
        out = _expert_ffn(buf, w1, w2, n, e_local, capacity, d_model,
                          axis_name, activation)
        y = _mk.combine_from_experts(out, expert, pos, gate)
    else:
        dispatch, combine, aux = moe_dispatch(x, router_w, num_experts,
                                              capacity)
        # gather tokens into expert buffers: (E, C, D)
        buf = jnp.einsum("nec,nd->ecd", dispatch, x.astype(jnp.float32))
        out = _expert_ffn(buf, w1, w2, n, e_local, capacity, d_model,
                          axis_name, activation)
        y = jnp.einsum("nec,ecd->nd", combine, out)
    aux = lax.pmean(aux, axis_name)
    return y.astype(x.dtype), aux


def moe_apply(x, router_w, w1, w2, mesh=None, axis_name="ep",
              capacity_factor=1.25, activation=jax.nn.gelu):
    """shard_map wrapper: x (N, D) sharded on tokens, experts sharded on
    `axis_name`; router replicated. Returns (y, aux_loss)."""
    from ._compat import shard_map

    mesh = mesh or current_mesh()
    fn = shard_map(
        lambda x_, r_, w1_, w2_: moe_ffn(
            x_, r_, w1_, w2_, axis_name, capacity_factor, activation),
        mesh=mesh,
        in_specs=(P(axis_name, None), P(None, None),
                  P(axis_name, None, None), P(axis_name, None, None)),
        out_specs=(P(axis_name, None), P()),
        check_vma=False)
    return fn(x, router_w, w1, w2)
