"""Sharding rule helpers.

GSPMD sharding annotations replace the reference's per-tensor kvstore traffic
(SURVEY.md §2.5). Parameters can carry explicit specs
(`Parameter.set_sharding`); these helpers fill in the rest.
"""
from __future__ import annotations

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import current_mesh

__all__ = ["param_spec", "batch_spec", "replicated", "fsdp_spec",
           "apply_tp_rules", "DATA_AXES"]

# both dp and fsdp are "data" axes from the batch's point of view
DATA_AXES = ("dp", "fsdp")


def replicated(mesh=None):
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, PartitionSpec())


def batch_spec(ndim, mesh=None, extra=None):
    """Batch sharded over the data axes on dim 0; rest replicated."""
    mesh = mesh or current_mesh()
    axes = [a for a in DATA_AXES if mesh.shape.get(a, 1) > 1] or list(DATA_AXES)
    spec = [tuple(axes)] + [None] * (ndim - 1)
    if extra:
        for dim, ax in extra.items():
            spec[dim] = ax
    return NamedSharding(mesh, PartitionSpec(*spec))


def fsdp_spec(shape, mesh=None):
    """ZeRO-style: shard the largest divisible dim over 'fsdp' (TPU analog of
    the reference's big-array round-robin across PS servers)."""
    mesh = mesh or current_mesh()
    size = mesh.shape.get("fsdp", 1)
    if size <= 1 or not shape:
        return replicated(mesh)
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
        if shape[dim] % size == 0 and shape[dim] >= size:
            spec = [None] * len(shape)
            spec[dim] = "fsdp"
            return NamedSharding(mesh, PartitionSpec(*spec))
    return replicated(mesh)


def param_spec(param, mesh=None, mode="replicate"):
    """Sharding for one Parameter: explicit set_sharding wins; else policy."""
    mesh = mesh or current_mesh()
    if param.sharding is not None:
        s = param.sharding
        if isinstance(s, PartitionSpec):
            return NamedSharding(mesh, s)
        return s
    if mode == "fsdp":
        return fsdp_spec(param.shape, mesh)
    return replicated(mesh)


def apply_tp_rules(block, rules):
    """Attach Megatron-style tp specs by parameter-path regex.

    rules: list of (regex, PartitionSpec). First match wins. Example for a
    transformer MLP: [(r'.*ffn_in.*weight', P('tp', None)),
                      (r'.*ffn_out.*weight', P(None, 'tp'))]."""
    import re
    for path, p in block.collect_params().items():
        for pattern, spec in rules:
            if re.search(pattern, path):
                p.set_sharding(spec)
                break
