"""Sharding rule helpers.

GSPMD sharding annotations replace the reference's per-tensor kvstore traffic
(SURVEY.md §2.5). Parameters can carry explicit specs
(`Parameter.set_sharding`); these helpers fill in the rest.
"""
from __future__ import annotations

import os

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import current_mesh

__all__ = ["param_spec", "batch_spec", "replicated", "fsdp_spec",
           "apply_tp_rules", "constrain_batch", "constrain_seq", "DATA_AXES",
           "spec_to_tree", "spec_from_tree"]

# both dp and fsdp are "data" axes from the batch's point of view
DATA_AXES = ("dp", "fsdp")


def replicated(mesh=None):
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, PartitionSpec())


def batch_spec(ndim, mesh=None, extra=None):
    """Batch sharded over the data axes on dim 0; rest replicated."""
    mesh = mesh or current_mesh()
    axes = [a for a in DATA_AXES if mesh.shape.get(a, 1) > 1] or list(DATA_AXES)
    spec = [tuple(axes)] + [None] * (ndim - 1)
    if extra:
        for dim, ax in extra.items():
            spec[dim] = ax
    return NamedSharding(mesh, PartitionSpec(*spec))


# Only shard params with at least this many elements over fsdp (reference:
# MXNET_KVSTORE_BIGARRAY_BOUND — small arrays are not worth distributing).
# Small 1D params (LayerNorm gamma/beta, biases) otherwise force a constant
# stream of GSPMD reshards around their broadcasts/reductions.
# Knob: config 'fsdp_min_size' / MXNET_TPU_FSDP_MIN_SIZE.


def _fsdp_min_size():
    from .. import config
    return config.get("fsdp_min_size")


def fsdp_spec(shape, mesh=None, hint=None):
    """ZeRO-style: shard the largest divisible dim over 'fsdp' (TPU analog of
    the reference's big-array round-robin across PS servers). Arrays smaller
    than FSDP_MIN_SIZE elements stay replicated.

    hint='embedding' (gather tables): replicate. GSPMD cannot partition a
    gather over the indexed dim (vocab-sharded → involuntary full
    rematerialization of the table), and feature-dim sharding forces the
    scatter-grad to reshard batch-sharded (B,L,E) updates onto the feature
    axis — another involuntary-remat pattern. Replication costs a little
    ZeRO memory on one table; explicit tp rules (e.g. BERT's feature-dim
    vocab projection sharding) still apply via set_sharding."""
    mesh = mesh or current_mesh()
    size = mesh.shape.get("fsdp", 1)
    if size <= 1 or not shape:
        return replicated(mesh)
    if hint == "embedding" or int(np.prod(shape)) < _fsdp_min_size():
        return replicated(mesh)
    if len(shape) == 2:
        # (out, in) Dense weights: prefer the contraction (input) dim — the
        # partitioned matmul then psums partial products and activations
        # stay batch-sharded. Output-dim sharding pushes feature shardings
        # onto activations, which GSPMD can only undo next to a gather by
        # involuntary full rematerialization.
        order = [1, 0]
    else:
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
        if shape[dim] % size == 0 and shape[dim] >= size:
            spec = [None] * len(shape)
            spec[dim] = "fsdp"
            return NamedSharding(mesh, PartitionSpec(*spec))
    return replicated(mesh)


def constrain_batch(x, mesh=None):
    """Pin an activation (jax array) to batch sharding over the data axes.

    Use after ops whose transpose is a scatter (gather/take_along_axis):
    without the pin, sharding propagation from a downstream fsdp-sharded
    weight can make the scatter's updates feature-sharded, which GSPMD can
    only reach from batch-sharded via involuntary full rematerialization.
    `with_sharding_constraint` transposes to itself, so the pin holds for
    the cotangent too. No-op when no data axis is sharded, or when the
    batch dim isn't divisible by the sharded data-axis product (e.g. eager
    small-batch inference with a big mesh active)."""
    import jax

    from .mesh import _manual
    if _manual:
        return x  # inside shard_map: arrays are per-shard, no constraints
    mesh = mesh or current_mesh()
    sharded = [a for a in DATA_AXES if mesh.shape.get(a, 1) > 1]
    if not sharded:
        return x
    total = int(np.prod([mesh.shape[a] for a in sharded]))
    if x.ndim == 0 or x.shape[0] % total != 0:
        return x
    return jax.lax.with_sharding_constraint(x, batch_spec(x.ndim, mesh))


def constrain_seq(x, mesh=None, seq_dim=1):
    """Pin a (B, L, ...) activation to batch sharding on dim 0 AND `sp`
    sharding on the sequence dim — the anchor that keeps long-context
    activations sequence-sharded between ring-attention shard_maps (without
    it GSPMD may all-gather L after the first elementwise op). Falls back
    to `constrain_batch` when sp is 1 or L does not divide."""
    import jax

    from .mesh import _manual
    if _manual:
        return x
    mesh = mesh or current_mesh()
    sp = mesh.shape.get("sp", 1)
    if sp <= 1 or x.ndim <= seq_dim or x.shape[seq_dim] % sp != 0:
        return constrain_batch(x, mesh)
    sharded = [a for a in DATA_AXES if mesh.shape.get(a, 1) > 1]
    # shard dim 0 over the largest axis subset whose product divides B —
    # pinning it to None would force an all-gather of a batch GSPMD may
    # already have sharded
    while sharded and x.shape[0] % int(
            np.prod([mesh.shape[a] for a in sharded])):
        sharded.pop()
    spec = [tuple(sharded) if sharded else None] + [None] * (x.ndim - 1)
    spec[seq_dim] = "sp"
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec)))


def param_spec(param, mesh=None, mode="replicate"):
    """Sharding for one Parameter: explicit set_sharding wins; else policy."""
    mesh = mesh or current_mesh()
    if param.sharding is not None:
        s = param.sharding
        if isinstance(s, PartitionSpec):
            return NamedSharding(mesh, s)
        return s
    if mode == "fsdp":
        return fsdp_spec(param.shape, mesh, getattr(param, "shard_hint", None))
    if mode != "replicate":
        # an unrecognized mode must not silently replicate — a typo like
        # "shard" would otherwise run (and test) the wrong configuration
        raise ValueError(f"param_mode {mode!r}: expected 'replicate' or "
                         "'fsdp'")
    return replicated(mesh)


def spec_to_tree(spec):
    """PartitionSpec (or NamedSharding) → a JSON-able list: one entry per
    dim, each None | axis-name | [axis-names]. The serialization the
    checkpoint manifest records per array so a restore on a DIFFERENT
    topology can plan the redistribution (parallel/reshard.py)."""
    if isinstance(spec, NamedSharding):
        spec = spec.spec
    out = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def spec_from_tree(tree):
    """Inverse of spec_to_tree."""
    entries = []
    for entry in tree or []:
        if entry is None or isinstance(entry, str):
            entries.append(entry)
        else:
            entries.append(tuple(entry))
    return PartitionSpec(*entries)


def apply_tp_rules(block, rules):
    """Attach Megatron-style tp specs by parameter-path regex.

    rules: list of (regex, PartitionSpec). First match wins. Example for a
    transformer MLP: [(r'.*ffn_in.*weight', P('tp', None)),
                      (r'.*ffn_out.*weight', P(None, 'tp'))]."""
    import re
    for path, p in block.collect_params().items():
        for pattern, spec in rules:
            if re.search(pattern, path):
                p.set_sharding(spec)
                break
