"""Ring attention: sequence/context parallelism over a mesh axis.

Net-new capability vs the reference (SURVEY.md §5.7 — MXNet has nothing that
shards the sequence dimension). Design: the sequence is sharded over the
`sp` mesh axis; each device holds local Q/K/V blocks. K/V blocks rotate
around the ring via `lax.ppermute` (XLA lowers to ICI collective-permute)
while each device accumulates its queries' attention online with
flash-style log-sum-exp merging.

Memory is O(L_local), not O(L_local^2): the per-block-pair attention is the
SAME blockwise kernel as single-chip flash attention — on TPU the Pallas
flash forward/backward kernels run per KV block (`pallas_ops/
flash_attention._flash_fwd_pallas` / `_flash_bwd_pallas` with the globally
merged LSE), on CPU test meshes a chunked `lax.scan` computes at most a
(L_local, chunk) score tile at a time. The whole ring is a `jax.custom_vjp`:
the backward pass is a second ring rotation in which dK/dV accumulators
travel WITH their K/V blocks and arrive home after n hops, so no L×L tensor
and no all-gather ever materializes.

Use under `shard_map` with the `sp` axis (see `ring_self_attention` /
`sp_self_attention`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["ring_attention", "ring_self_attention", "sp_self_attention"]

_NEG = -1e30
_DEFAULT_CHUNK = 512


def _fit_chunk(chunk, L):
    """Largest divisor of L that is <= chunk (scan needs equal chunks)."""
    c = max(1, min(int(chunk), int(L)))
    while L % c:
        c -= 1
    return c


# --------------------------------------------------------------------------
# inner per-block-pair kernels: (q_block x kv_block) -> normalized (o, lse)
# and the matching backward.  Two implementations, one contract:
#   fwd: (B,H,Lq,D)x(B,H,Lk,D) + bias (B,Lk) -> o (B,H,Lq,D) f32, lse (B,H,Lq) f32
#   bwd: given global (o, lse) and upstream g -> (dq, dk, dv) f32
# `causal` here means causal WITHIN the block pair (Lq == Lk, offset 0) —
# the only causal case the ring needs (the diagonal block src == my).
# --------------------------------------------------------------------------


def _chunked_fwd(q, k, v, bias, causal, sm_scale, chunk):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    C = _fit_chunk(chunk, Lk)
    nc = Lk // C
    q32 = q.astype(jnp.float32)
    kc = jnp.moveaxis(k.reshape(B, H, nc, C, D), 2, 0)
    vc = jnp.moveaxis(v.reshape(B, H, nc, C, D), 2, 0)
    bc = jnp.moveaxis(bias.reshape(B, nc, C), 1, 0)
    rows = jnp.arange(Lq)[:, None]

    def body(carry, blk):
        m, l, acc = carry
        kb, vb, bb, ci = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kb.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        s = s + bb[:, None, None, :]
        if causal:
            cols = ci * C + jnp.arange(C)[None, :]
            s = jnp.where(cols <= rows, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       vb.astype(jnp.float32))
        return (m_new, l, acc), None

    m0 = jnp.full((B, H, Lq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Lq, 1), jnp.float32)
    a0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    (m, l, acc), _ = lax.scan(body, (m0, l0, a0),
                              (kc, vc, bc, jnp.arange(nc)))
    l = jnp.maximum(l, 1e-30)
    return acc / l, (m + jnp.log(l))[..., 0]


def _chunked_bwd(q, k, v, bias, g, lse, delta, causal, sm_scale, chunk):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    C = _fit_chunk(chunk, Lk)
    nc = Lk // C
    q32 = q.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    kc = jnp.moveaxis(k.reshape(B, H, nc, C, D), 2, 0)
    vc = jnp.moveaxis(v.reshape(B, H, nc, C, D), 2, 0)
    bc = jnp.moveaxis(bias.reshape(B, nc, C), 1, 0)
    rows = jnp.arange(Lq)[:, None]
    lse_c = lse[..., None]
    delta_c = delta[..., None]

    def body(dq, blk):
        kb, vb, bb, ci = blk
        kb32 = kb.astype(jnp.float32)
        vb32 = vb.astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kb32,
                       preferred_element_type=jnp.float32) * sm_scale
        s = s + bb[:, None, None, :]
        if causal:
            cols = ci * C + jnp.arange(C)[None, :]
            s = jnp.where(cols <= rows, s, _NEG)
        p = jnp.exp(s - lse_c)                       # true probabilities
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, vb32)
        ds = p * (dp - delta_c) * sm_scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb32)
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
        return dq, (dk_c, dv_c)

    dq0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    dq, (dk_c, dv_c) = lax.scan(body, dq0, (kc, vc, bc, jnp.arange(nc)))
    dk = jnp.moveaxis(dk_c, 0, 2).reshape(B, H, Lk, D)
    dv = jnp.moveaxis(dv_c, 0, 2).reshape(B, H, Lk, D)
    return dq, dk, dv


def _use_pallas(q, k):
    from ..pallas_ops.flash_attention import has_pallas, _interpret
    return ((jax.default_backend() == "tpu" or _interpret())
            and has_pallas()
            and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0)


def _inner_fwd(q, k, v, bias, causal, sm_scale, chunk, use_pallas):
    if use_pallas:
        from ..pallas_ops.flash_attention import (_fit_block,
                                                  _flash_fwd_pallas)
        bq = _fit_block(512, q.shape[2])
        bk = _fit_block(512, k.shape[2])
        seed = jnp.zeros((1,), jnp.int32)
        o, lse8 = _flash_fwd_pallas(q, k, v, bias, seed, causal, sm_scale,
                                    bq, bk, 0.0)
        B, H, L, _ = q.shape
        return o.astype(jnp.float32), lse8[:, 0, :].reshape(B, H, L)
    return _chunked_fwd(q, k, v, bias, causal, sm_scale, chunk)


def _inner_bwd(q, k, v, bias, g, o, lse, delta, causal, sm_scale, chunk,
               use_pallas):
    if use_pallas:
        from ..pallas_ops.flash_attention import (_fit_block,
                                                  _flash_bwd_pallas, _row8)
        B, H, L, _ = q.shape
        bq = _fit_block(512, q.shape[2])
        bk = _fit_block(512, k.shape[2])
        seed = jnp.zeros((1,), jnp.int32)
        lse8 = _row8(lse.reshape(B * H, L))
        dq, dk, dv = _flash_bwd_pallas(
            q, k, v, bias, seed, o.astype(q.dtype), lse8, g, causal,
            sm_scale, bq, bk, 0.0)
        return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                dv.astype(jnp.float32))
    return _chunked_bwd(q, k, v, bias, g, lse, delta, causal, sm_scale, chunk)


# --------------------------------------------------------------------------
# the ring itself (custom_vjp; call inside shard_map)
# --------------------------------------------------------------------------


def _merge(o_acc, lse_acc, o_blk, lse_blk):
    """Merge two NORMALIZED partial attentions by their log-sum-exps."""
    m = jnp.maximum(lse_acc, lse_blk)
    wa = jnp.exp(lse_acc - m)
    wb = jnp.exp(lse_blk - m)
    w = wa + wb
    o = (o_acc * wa[..., None] + o_blk * wb[..., None]) / w[..., None]
    return o, m + jnp.log(w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring(q, k, v, bias, axis_name, causal, sm_scale, chunk):
    out, _ = _ring_fwd(q, k, v, bias, axis_name, causal, sm_scale, chunk)
    return out


def _ring_fwd(q, k, v, bias, axis_name, causal, sm_scale, chunk):
    n = lax.psum(1, axis_name)          # static: axis size
    my = lax.axis_index(axis_name)
    use_pallas = _use_pallas(q, k)
    B, H, L, D = q.shape

    o_acc = jnp.zeros((B, H, L, D), jnp.float32)
    lse_acc = jnp.full((B, H, L), _NEG, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur, b_cur = k, v, bias

    def full_blk(kv):
        return _inner_fwd(q, kv[0], kv[1], kv[2], False, sm_scale, chunk,
                          use_pallas)

    def caus_blk(kv):
        return _inner_fwd(q, kv[0], kv[1], kv[2], True, sm_scale, chunk,
                          use_pallas)

    def masked_blk(kv):
        return (jnp.zeros((B, H, L, D), jnp.float32),
                jnp.full((B, H, L), _NEG, jnp.float32))

    # python loop of static length n: unrolled into the XLA program so each
    # ppermute overlaps the previous block's compute
    for step in range(n):
        src = (my - step) % n           # which shard's kv we currently hold
        if causal:
            # shard-level causality: src < my → full block; == → causal
            # within the block; > → entirely masked (selected at runtime —
            # src is traced — via lax.switch, so only ONE branch executes)
            idx = jnp.where(src == my, 1, jnp.where(src > my, 2, 0))
            o_blk, lse_blk = lax.switch(
                idx, [full_blk, caus_blk, masked_blk], (k_cur, v_cur, b_cur))
        else:
            o_blk, lse_blk = full_blk((k_cur, v_cur, b_cur))
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_blk, lse_blk)
        if step < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
            b_cur = lax.ppermute(b_cur, axis_name, perm)

    return o_acc.astype(q.dtype), (q, k, v, bias, o_acc, lse_acc)


def _ring_bwd(axis_name, causal, sm_scale, chunk, res, g):
    q, k, v, bias, o, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    use_pallas = _use_pallas(q, k)
    B, H, L, D = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * o, axis=-1)      # (B,H,L)

    dq = jnp.zeros((B, H, L, D), jnp.float32)
    dk_acc = jnp.zeros((B, H, L, D), jnp.float32)
    dv_acc = jnp.zeros((B, H, L, D), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur, b_cur = k, v, bias

    def full_blk(kv):
        return _inner_bwd(q, kv[0], kv[1], kv[2], g, o, lse, delta, False,
                          sm_scale, chunk, use_pallas)

    def caus_blk(kv):
        return _inner_bwd(q, kv[0], kv[1], kv[2], g, o, lse, delta, True,
                          sm_scale, chunk, use_pallas)

    def masked_blk(kv):
        z = jnp.zeros((B, H, L, D), jnp.float32)
        return z, z, z

    # second ring pass: dK/dV accumulators TRAVEL WITH their K/V blocks —
    # after n hops (note: n, not n-1; the kv blocks themselves only need
    # n-1) each accumulator has collected every device's contribution and
    # is back on the device that owns that sequence shard
    for step in range(n):
        src = (my - step) % n
        if causal:
            idx = jnp.where(src == my, 1, jnp.where(src > my, 2, 0))
            dq_b, dk_b, dv_b = lax.switch(
                idx, [full_blk, caus_blk, masked_blk], (k_cur, v_cur, b_cur))
        else:
            dq_b, dk_b, dv_b = full_blk((k_cur, v_cur, b_cur))
        dq = dq + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        if step < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
            b_cur = lax.ppermute(b_cur, axis_name, perm)

    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype), jnp.zeros_like(bias))


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(q, k, v, axis_name, mask=None, causal=False, sm_scale=None,
                   chunk=_DEFAULT_CHUNK):
    """Attention over a ring: call INSIDE shard_map with seq sharded on
    `axis_name`. q,k,v: (B, H, L_local, D) per device; mask: (B, L_local)
    local padding mask (True = attend). Differentiable (custom VJP; the
    backward is a second ring pass). Attention-probability dropout is not
    supported under the ring (the reference fused attention it replaces is
    a single-chip op; see `pallas_ops.flash_attention` for that)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if mask is not None:
        bias = jnp.where(mask.astype(bool), 0.0, _NEG).astype(jnp.float32)
    else:
        bias = jnp.zeros((q.shape[0], k.shape[2]), jnp.float32)
    return _ring(q, k, v, bias, axis_name, causal, float(sm_scale),
                 int(chunk))


def ring_self_attention(q, k, v, mask=None, causal=False, mesh=None,
                        axis_name="sp"):
    """Convenience wrapper: shard_map over the mesh's `sp` axis with
    (B, H, L, D) global tensors; L is sharded."""
    from ._compat import shard_map

    mesh = mesh or current_mesh()
    qspec = P(None, None, axis_name, None)
    mspec = P(None, axis_name)

    if mask is not None:
        fn = shard_map(
            lambda q_, k_, v_, m_: ring_attention(
                q_, k_, v_, axis_name, mask=m_, causal=causal),
            mesh=mesh, in_specs=(qspec, qspec, qspec, mspec), out_specs=qspec,
            check_vma=False)
        return fn(q, k, v, mask)
    fn = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis_name, causal=causal),
        mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
        check_vma=False)
    return fn(q, k, v)


def sp_self_attention(q, k, v, mask=None, causal=False, mesh=None,
                      axis_name="sp", inner=None):
    """Ring attention inside a FULL training mesh: shard_map over every mesh
    axis with batch kept on the data axes, heads on `tp` (when divisible)
    and the sequence on `axis_name`, so it composes with dp/fsdp/tp GSPMD
    sharding in a jitted train step (the flagship sp path — SURVEY §5.7).

    q,k,v: GLOBAL (B, H, L, D); mask: global (B, L).
    inner: the per-shard attention (q, k, v, axis_name, mask=, causal=) —
    defaults to `ring_attention`; pass `ulysses.ulysses_attention` for the
    all-to-all head↔sequence reshard instead of the ring."""
    from ._compat import shard_map

    mesh = mesh or current_mesh()
    B, H, L, D = q.shape
    if L % mesh.shape.get(axis_name, 1):
        raise ValueError(
            f"sequence length {L} not divisible by {axis_name} axis size "
            f"{mesh.shape.get(axis_name, 1)}")
    import numpy as np

    from .specs import DATA_AXES
    data = [a for a in DATA_AXES if mesh.shape.get(a, 1) > 1]
    # B must divide the PRODUCT of the included axes; drop axes until it does
    while data and B % int(np.prod([mesh.shape[a] for a in data])):
        data.pop()
    bspec = tuple(data) if data else None
    tp = mesh.shape.get("tp", 1)
    hspec = "tp" if (tp > 1 and H % tp == 0) else None
    qspec = P(bspec, hspec, axis_name, None)
    mspec = P(bspec, axis_name)
    attn = inner or ring_attention

    if mask is not None:
        fn = shard_map(
            lambda q_, k_, v_, m_: attn(
                q_, k_, v_, axis_name, mask=m_, causal=causal),
            mesh=mesh, in_specs=(qspec, qspec, qspec, mspec), out_specs=qspec,
            check_vma=False)
        return fn(q, k, v, mask)
    fn = shard_map(
        lambda q_, k_, v_: attn(q_, k_, v_, axis_name, causal=causal),
        mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
        check_vma=False)
    return fn(q, k, v)
