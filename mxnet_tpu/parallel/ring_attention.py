"""Ring attention: sequence/context parallelism over a mesh axis.

Net-new capability vs the reference (SURVEY.md §5.7 — MXNet has nothing that
shards the sequence dimension). Design: the sequence is sharded over the
`sp` mesh axis; each device holds local Q/K/V blocks. K/V blocks rotate
around the ring via `lax.ppermute` (XLA lowers to ICI collective-permute)
while each device accumulates its queries' attention online — flash-style
log-sum-exp merging, so memory stays O(L_local) and compute overlaps the
rotation. Use under `shard_map` with the `sp` axis (see `ring_self_attention`).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import current_mesh

__all__ = ["ring_attention", "ring_self_attention"]

_NEG = -1e30


def _block_attn(q, k, v, bias, causal_mode, sm_scale):
    """One q-block × kv-block attention returning (out_unnorm, m, l).

    causal_mode: 0 = full attention, 1 = causal within block, 2 = all masked.
    Shapes: q (B,H,Lq,D), k/v (B,H,Lk,D), bias (B,Lk) additive.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias[:, None, None, :]
    Lq, Lk = q.shape[2], k.shape[2]
    if causal_mode == 1:
        row = jnp.arange(Lq)[:, None] + (Lk - Lq)
        col = jnp.arange(Lk)[None, :]
        s = jnp.where(col <= row, s, _NEG)
    elif causal_mode == 2:
        s = jnp.full_like(s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)                      # (B,H,Lq,1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out, m, l


def ring_attention(q, k, v, axis_name, mask=None, causal=False, sm_scale=None):
    """Attention over a ring: call INSIDE shard_map with seq sharded on
    `axis_name`. q,k,v: (B, H, L_local, D) per device; mask: (B, L_local)
    local padding mask (True = attend).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    bias = None
    if mask is not None:
        bias = jnp.where(mask.astype(bool), 0.0, _NEG).astype(jnp.float32)

    B, H, L, D = q.shape
    m_acc = jnp.full((B, H, L, 1), _NEG, jnp.float32)
    l_acc = jnp.zeros((B, H, L, 1), jnp.float32)
    o_acc = jnp.zeros((B, H, L, D), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(carry, blk):
        m_acc, l_acc, o_acc = carry
        o_blk, m_blk, l_blk = blk
        m_new = jnp.maximum(m_acc, m_blk)
        a = jnp.exp(m_acc - m_new)
        b = jnp.exp(m_blk - m_new)
        return (m_new, l_acc * a + l_blk * b, o_acc * a + o_blk * b)

    k_cur, v_cur, b_cur = k, v, bias if bias is not None else jnp.zeros((B, L), jnp.float32)
    carry = (m_acc, l_acc, o_acc)
    # python loop of static length n: unrolled into the XLA program so each
    # ppermute overlaps the previous block's compute
    for step in range(n):
        src = (my - step) % n  # which shard's kv we currently hold
        if causal:
            # shard-level causality: src < my → full; == → causal; > → masked.
            # All three variants are computed branch-free via masks on a
            # traced predicate (src is traced).
            s_full, m_full, l_full = _block_attn(q, k_cur, v_cur, b_cur, 0, sm_scale)
            s_caus, m_caus, l_caus = _block_attn(q, k_cur, v_cur, b_cur, 1, sm_scale)
            is_caus = (src == my)
            is_masked = (src > my)
            o_blk = jnp.where(is_caus, s_caus, s_full)
            m_blk = jnp.where(is_caus, m_caus, m_full)
            l_blk = jnp.where(is_caus, l_caus, l_full)
            m_blk = jnp.where(is_masked, jnp.full_like(m_blk, _NEG), m_blk)
            l_blk = jnp.where(is_masked, jnp.zeros_like(l_blk), l_blk)
            o_blk = jnp.where(is_masked, jnp.zeros_like(o_blk), o_blk)
        else:
            o_blk, m_blk, l_blk = _block_attn(q, k_cur, v_cur, b_cur, 0, sm_scale)
        carry = merge(carry, (o_blk, m_blk, l_blk))
        if step < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
            b_cur = lax.ppermute(b_cur, axis_name, perm)

    m_acc, l_acc, o_acc = carry
    return (o_acc / jnp.maximum(l_acc, 1e-30)).astype(q.dtype)


def ring_self_attention(q, k, v, mask=None, causal=False, mesh=None,
                        axis_name="sp"):
    """Convenience wrapper: shard_map over the mesh's `sp` axis with
    (B, H, L, D) global tensors; L is sharded."""
    from jax import shard_map

    mesh = mesh or current_mesh()
    qspec = P(None, None, axis_name, None)
    mspec = P(None, axis_name)

    if mask is not None:
        fn = shard_map(
            lambda q_, k_, v_, m_: ring_attention(
                q_, k_, v_, axis_name, mask=m_, causal=causal),
            mesh=mesh, in_specs=(qspec, qspec, qspec, mspec), out_specs=qspec,
            check_vma=False)
        return fn(q, k, v, mask)
    fn = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis_name, causal=causal),
        mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
        check_vma=False)
    return fn(q, k, v)
