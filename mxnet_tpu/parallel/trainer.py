"""ShardedTrainer: one jitted, mesh-sharded train step.

This is the TPU-native performance path the reference cannot express: where
the reference runs eager-op forward, tape backward, then per-parameter
kvstore push/pull + update ops (`gluon/trainer.py` step → `src/kvstore/*`),
here the ENTIRE step — forward, loss, backward, gradient reduction (XLA psum
over the data axes), optimizer — is one XLA computation over a named mesh.
Parameters/optimizer state live device-resident and donated between steps;
gradient reduction rides ICI; fsdp mode shards params + optimizer state
(weight-update sharding).

Gluon blocks plug in unchanged via `gluon.functional_call`.
"""
from __future__ import annotations

import contextlib
import math
import time

import jax
import jax.numpy as jnp

from .. import random as _random
from .. import _engine
from .. import check as _check
from .. import config as _config
from .. import diagnostics as _diagnostics
from .. import goodput as _goodput
from .. import guard as _guard
from .. import inspect as _inspect
from .. import memsafe as _memsafe
from .. import resilience as _resilience
from .. import scope as _scope
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..gluon.block import functional_call
from ..ndarray import NDArray
from . import specs as _specs
from . import zero as _zero
from .functional_opt import FunctionalOptimizer
from .mesh import current_mesh

__all__ = ["ShardedTrainer", "call_loss"]

# reusable do-nothing context for the unsampled/disabled trace path (a
# fresh nullcontext per step would be an allocation on the hot path)
_NULLCTX = contextlib.nullcontext()

# shared, framework-wide series (get-or-create: same objects as the
# HybridBlock jit cache and the gluon Trainer register)
_M_COMPILES = _telemetry.counter("compile_total")
_M_RECOMPILES = _telemetry.counter("recompile_total")
_M_COMPILE_SECONDS = _telemetry.histogram("compile_seconds")
_M_STEP_SECONDS = _telemetry.histogram("trainer_step_seconds")
_M_COLL_CALLS = _telemetry.counter(
    "collective_calls_total", "XLA collectives issued per jitted train step "
    "(host-side accounting: the gradient psum on the data axes — or, on a "
    "mx.zero'd trainer, the gradient reduce-scatter + updated-param "
    "all-gather pair)")
_M_COLL_BYTES = _telemetry.counter(
    "collective_bytes_total", "payload bytes moved by the counted "
    "collectives (gradient/param bytes per reducing step, labeled by op)")


def call_loss(loss_fn, rng, outs, labels):
    """Invoke a user loss_fn on raw arrays inside a traced train step:
    recording off, training mode on, loss RNG pinned to fold_in(rng, 1).
    Shared by ShardedTrainer and PipelineTrainer so the engine-flag and
    rng conventions cannot drift between them."""
    prev_r = _engine.set_recording(False)
    prev_t = _engine.set_training(True)
    try:
        with _random.key_scope(jax.random.fold_in(rng, 1)):
            loss_nd = loss_fn(*[NDArray(o) for o in outs],
                              *[NDArray(l) for l in labels])
    finally:
        _engine.set_recording(prev_r)
        _engine.set_training(prev_t)
    return jnp.mean(loss_nd._data.astype(jnp.float32))


class ShardedTrainer:
    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, param_mode="replicate", donate=True,
                 data_specs=None, label_specs=None):
        """data_specs/label_specs: optional per-array PartitionSpec overrides
        for the batch inputs (None entries fall back to the default
        batch-on-data-axes spec) — e.g. P(('dp','fsdp'), 'sp') to shard
        token sequences for long-context/ring-attention training."""
        from .. import optimizer as opt_mod
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh or current_mesh()
        self.param_mode = param_mode
        self._data_specs = list(data_specs) if data_specs else []
        self._label_specs = list(label_specs) if label_specs else []
        self._opt = opt_mod.create(optimizer, **(optimizer_params or {})) \
            if isinstance(optimizer, str) else optimizer
        self._donate = donate
        self.num_update = 0
        self._step_cache = {}
        self._ready = False
        self._tele_sig = None
        self._tele_reduce_bytes = 0
        self._tele_coll = {}
        self._coll_est = {}
        self._zero = False
        self._zero_specs = None
        self._zero_flat = None
        # gradient-accumulation factor (mx.memsafe degradation ladder /
        # set_grad_accum): the jitted step splits the global batch into
        # this many microbatches, accumulating grads — loss/grad parity
        # with the full batch up to reduction order
        self._accum = 1
        # arm memsafe/check iff their knobs ask (oom_recover=auto /
        # device_bytes_limit / check!=off): construction-time config
        # reads only — the step hot path keeps its single module-bool
        # check per subsystem
        _memsafe.maybe_enable()
        _check.maybe_enable()
        _guard.maybe_enable()
        _scope.maybe_enable()
        # persistent XLA compilation cache (compile_cache_dir knob): wired
        # once, at first trainer construction, before anything compiles
        from .. import dataflow as _dataflow
        _dataflow.ensure_compile_cache()
        from ..gluon.parameter import DeferredInitializationError
        try:
            self._setup()
        except DeferredInitializationError:
            # deferred parameter shapes: resolved by an eager probe pass on
            # the first step's batch (reference: deferred init on forward)
            pass
        if _resilience._enabled:
            # auto-resume per the `resume` knob: restore params/optimizer/
            # RNG/device-step-counter from the newest VERIFIED checkpoint
            # before any step runs (one module-bool check when disabled)
            _resilience.on_trainer_init(self)

    def _setup(self):
        self._fn, self._grad_params, self._aux_params = functional_call(
            self.block, train=True)
        self._names = [name for name, _ in self._grad_params]
        self.fopt = FunctionalOptimizer(self._opt, self._names)

        # shardings
        self._pshard = [
            _specs.param_spec(p, self.mesh, self.param_mode)
            for _, p in self._grad_params]
        self._aux_shard = [_specs.replicated(self.mesh) for _ in self._aux_params]
        rep = _specs.replicated(self.mesh)
        self._rep = rep

        # Fused multi-tensor LAMB + f32 flat master weights (reference
        # multi_mp_lamb_update): replicate mode only — under fsdp/tp the
        # per-parameter path shards cleanly, the flat concat would not.
        from .. import config
        self._fused = (
            self.fopt.kind == "lamb" and self.param_mode == "replicate"
            and config.get("fused_lamb"))
        # mx.zero: shard optimizer state (fused-LAMB masters included)
        # across the data axes per the `zero` knob. With the knob off
        # (default) this whole region is one module-bool check — no call
        # into the zero module at all (ci/run.sh sanity asserts it)
        self._zero = False
        self._zero_specs = None       # per-param opt-state shardings
        self._zero_flat = None        # fused flat master/moment sharding
        _zero.maybe_enable()
        zero_want = _zero._enabled and _config.get("zero") != "off"
        if self._fused:
            from .fused_lamb import FusedLamb
            o = self.fopt.opt
            datas = [p.data()._data for _, p in self._grad_params]
            self._fl = FusedLamb(
                [d.shape for d in datas], [d.dtype for d in datas],
                [self.fopt._wd_for(i) for i in range(len(datas))],
                o.beta1, o.beta2, o.epsilon, o.bias_correction,
                o.rescale_grad, o.clip_gradient or -1.0,
                o.lower_bound or -1.0, o.upper_bound or -1.0,
                moments_dtype=config.get("lamb_moments_dtype"))
            if zero_want:
                self._zero_flat = _zero.flat_spec(self._fl, self.mesh)
                self._zero = self._zero_flat is not None
            master = self._fl.flatten(datas)
            pspec = self._zero_flat if self._zero else rep
            self.params = jax.device_put(master, pspec)
            mdt = self._fl.moments_dtype
            self.opt_state = (
                jax.device_put(jnp.zeros(master.shape, mdt), pspec),
                jax.device_put(jnp.zeros(master.shape, mdt), pspec))
        else:
            self.params = [jax.device_put(p.data()._data, s)
                           for (_, p), s in zip(self._grad_params, self._pshard)]
            # optimizer state shards like its parameter (weight-update
            # sharding) — under mx.zero, additionally across the free
            # data axes (reduce-scatter/all-gather weight update)
            states = self.fopt.init(self.params)
            if zero_want:
                self._zero_specs = _zero.plan_state(
                    self.params, self._pshard, states, self.mesh)
                self._zero = any(s is not None for s in self._zero_specs)
                if not self._zero:
                    self._zero_specs = None
            self.opt_state = [
                tuple(jax.device_put(z, zs or s) for z in st)
                for st, zs, s in zip(
                    states,
                    self._zero_specs or [None] * len(states),
                    self._pshard)]
        if zero_want and not self._zero and _config.get("zero") == "on":
            raise ValueError(
                "zero='on' but nothing can shard: the mesh's data axes "
                f"span {_zero.data_extent(self.mesh)} device(s) and/or no "
                "optimizer-state buffer clears zero_min_size with a "
                "divisible dim. Use zero='auto' to no-op silently.")
        self.aux = [jax.device_put(p.data()._data, s)
                    for (_, p), s in zip(self._aux_params, self._aux_shard)]
        # the step counter lives ON DEVICE, incremented inside the jitted
        # step and donated like the rest of the train state: the hot path
        # then ships zero per-step scalars (the old host-side t/lr pair
        # cost two H2D transfers per step). int32 so `t + 1` stays exact
        # past 2^24 steps (a float32 counter would silently freeze there,
        # and with it the lr schedule and bias correction). When the lr
        # schedule is traceable (lr_traced), lr is computed from it inside
        # the step too; otherwise lr falls back to a host-computed traced
        # argument.
        self._t_dev = jax.device_put(
            jnp.asarray(self.num_update, jnp.int32), rep)
        self._lr_inside = self.fopt.lr_traced() is not None
        self._refresh_comm_estimates()
        self._ready = True

    def _refresh_comm_estimates(self):
        """Mesh-derived accounting for the CURRENT mesh + shardings:
        gradient-reduction payload for the collective counters and the
        mx.inspect per-collective traffic estimate. Called from _setup
        and again after an elastic resize or set_zero changes the
        layout."""
        # gradient-reduction payload per step, for the collective counters:
        # XLA psums grads over the data axes iff they span >1 device; a
        # mx.zero'd param instead reduce-scatters its gradient and
        # all-gathers its updated value (same payload, different ops)
        reduce_degree = self.mesh.shape.get("dp", 1) * \
            self.mesh.shape.get("fsdp", 1)
        if self._fused:
            nbytes = int(self.params.size * self.params.dtype.itemsize)
            entries = [(nbytes, self._rep, self._zero)]
        else:
            zflags = self._zero_specs or [None] * len(self.params)
            entries = [(int(p.size * p.dtype.itemsize), s, zs is not None)
                       for p, s, zs in zip(self.params, self._pshard,
                                           zflags)]
        psum_b = rs_b = ag_b = 0
        if reduce_degree > 1:
            for nbytes, _s, z in entries:
                if z:
                    rs_b += nbytes
                    ag_b += nbytes
                else:
                    psum_b += nbytes
        self._tele_reduce_bytes = psum_b + rs_b
        self._tele_coll = {op: n for op, n in (
            ("psum_grad", psum_b), ("reduce_scatter_grad", rs_b),
            ("all_gather_param", ag_b)) if n}
        # per-collective traffic estimate (mx.inspect): bytes each step's
        # gradient reduction / fsdp gather-scatter / zero reduce-scatter+
        # all-gather moves, from the specs just chosen + mesh shape.
        # One-time host arithmetic at setup
        self._coll_est = _inspect.estimate_collectives(
            self.mesh, [(n, s) for n, s, _z in entries],
            zero=[z for _n, _s, z in entries])

    # ------------------------------------------------------------------
    def _build_step(self, n_data, n_label, batch_shapes):
        fn = self._fn
        loss_fn = self.loss_fn
        fopt = self.fopt
        fused = self._fused
        fl = self._fl if fused else None
        # mx.zero: the sharded-update wiring is baked into the step at
        # build time (set_zero clears the step cache); with zero off all
        # three stay None/empty and the step body is byte-identical to
        # the classic path
        zflat = self._zero_flat if (self._zero and fused) else None
        zspecs = self._zero_specs if (self._zero and not fused) else None
        pshard_l = self._pshard if not fused else None
        rep_sh = self._rep
        accum = int(self._accum)
        if accum > 1:
            for shape in batch_shapes:
                if not shape or shape[0] % accum:
                    raise ValueError(
                        f"grad accumulation x{accum}: every batch/label "
                        f"array needs a leading dim divisible by {accum}, "
                        f"got shape {shape}")
        # re-snapshotted per build: a constant-lr schedule bakes the
        # CURRENT o.lr into the executable (the step-cache key carries the
        # value, so set_learning_rate costs one warm re-jit, not a
        # per-step transfer)
        lr_fn = self.fopt.lr_traced() if self._lr_inside else None

        def step(params, aux, opt_state, t, *rest):
            if lr_fn is None:
                lr, rng = rest[0], rest[1]
                batch = rest[2:]
            else:
                rng = rest[0]
                batch = rest[1:]
            t = t + 1            # device-resident num_update (int32: exact)
            tf = t.astype(jnp.float32)
            if lr_fn is not None:
                lr = lr_fn(tf)
            data, labels = batch[:n_data], batch[n_data:]

            def loss_of(ps, aux_in, data, labels, rng):
                if fused:
                    # per-tensor model-dtype views of the flat f32 master;
                    # the vjp of this unflatten returns the gradient FLAT
                    ps = fl.unflatten(ps)
                outs, new_aux = fn(ps, aux_in, rng, *data)
                loss = call_loss(loss_fn, rng, outs, labels)
                return loss, new_aux

            fwd_params = params
            if zflat is not None:
                # zero'd fused LAMB: the RESIDENT master is sharded; the
                # forward needs the whole vector, so gather it once here
                # (in-jit — XLA overlaps the all-gather with whatever
                # else is ready). Gradients are taken wrt this gathered
                # value, then reduce-SCATTERED below instead of psum'd.
                fwd_params = _zero.constrain(params, rep_sh)

            if accum <= 1:
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(fwd_params, aux, data, labels,
                                           rng)
            else:
                # gradient-accumulation microbatching (mx.memsafe
                # degradation ladder): lax.scan over `accum` equal slices
                # of the batch, summing grads — activation memory is one
                # microbatch's, and mean-of-means == full-batch mean for
                # equal chunks, so loss/grad match the unsplit step up to
                # reduction order. Each microbatch folds its index into
                # the step rng so dropout draws stay distinct, and aux
                # state (BatchNorm running stats) CHAINS through the scan
                # carry so every microbatch's update lands, not just the
                # last one's.
                split = [b.reshape((accum, b.shape[0] // accum)
                                   + b.shape[1:]) for b in batch]

                def micro(carry, xs):
                    g_acc, l_acc, aux_c = carry
                    i, mb = xs[0], list(xs[1:])
                    (l, na), g = jax.value_and_grad(
                        loss_of, has_aux=True)(
                            fwd_params, aux_c, mb[:n_data], mb[n_data:],
                            jax.random.fold_in(rng, i))
                    g_acc = jax.tree.map(jnp.add, g_acc, g)
                    return (g_acc, l_acc + l, na), None

                g0 = jax.tree.map(jnp.zeros_like, fwd_params)
                (g_sum, l_sum, new_aux), _ = jax.lax.scan(
                    micro, (g0, jnp.zeros((), jnp.float32), list(aux)),
                    (jnp.arange(accum),) + tuple(split))
                grads = jax.tree.map(lambda g: g / accum, g_sum)
                loss = l_sum / accum
            if fused:
                if zflat is not None:
                    # reduce-scatter the flat gradient: each device lands
                    # the shard matching its resident master/moments
                    grads = _zero.constrain(grads, zflat)
                new_params, new_m, new_v = fl.apply_flat(
                    params, grads, opt_state[0], opt_state[1], tf, lr)
                new_opt = (new_m, new_v)
            elif zspecs is not None:
                # mx.zero weight-update sharding (arxiv 2004.13336):
                # reduce-scatter each zero'd gradient, slice the matching
                # param shard (free — a sharding constraint, no movement),
                # run the optimizer on 1/D of the elements, then
                # all-gather the updated param back to its resident
                # layout. XLA emits the collectives from the constraints
                # and can overlap the all-gather with the tail of
                # backward; non-zero'd params (tiny state) keep the psum
                grads = [g if zs is None else _zero.constrain(g, zs)
                         for g, zs in zip(grads, zspecs)]
                w_upd = [p if zs is None else _zero.constrain(p, zs)
                         for p, zs in zip(params, zspecs)]
                new_params, new_opt = fopt.apply(w_upd, grads, opt_state,
                                                 tf, lr)
                new_params = [w if zs is None else _zero.constrain(w, ps)
                              for w, zs, ps in zip(new_params, zspecs,
                                                   pshard_l)]
            else:
                new_params, new_opt = fopt.apply(params, grads, opt_state,
                                                 tf, lr)
            return loss, new_params, new_aux, new_opt, t

        donate = (0, 1, 2, 3) if self._donate else (3,)
        if fused:
            pshard = zflat if zflat is not None else self._rep
            oshard = (pshard, pshard)
        else:
            pshard = self._pshard
            # zero'd opt state goes in AND comes out in its sharded
            # layout — identical avals + shardings, so donation aliases
            # cleanly (no double-buffering; mx.check stays quiet)
            zs_l = zspecs or [None] * len(self.opt_state)
            oshard = [tuple((zs or s) for _ in st)
                      for st, zs, s in zip(self.opt_state, zs_l,
                                           self._pshard)]
        scalar_in = () if lr_fn is not None else (self._rep,)
        in_shardings = (
            pshard, self._aux_shard, oshard, self._rep,
        ) + scalar_in + (self._rep,) \
            + tuple(self._batch_shardings(n_data, n_label, batch_shapes))
        out_shardings = (self._rep, pshard, self._aux_shard, oshard,
                         self._rep)
        return jax.jit(step, donate_argnums=donate,
                       in_shardings=in_shardings, out_shardings=out_shardings)

    # ------------------------------------------------------------------
    def _batch_shardings(self, n_data, n_label, shapes):
        from jax.sharding import NamedSharding

        overrides = (self._data_specs + [None] * n_data)[:n_data] + \
            (self._label_specs + [None] * n_label)[:n_label]
        return [NamedSharding(self.mesh, ov) if ov is not None
                else _specs.batch_spec(len(shape), self.mesh)
                for ov, shape in zip(overrides, shapes)]

    # ------------------------------------------------------------------
    def step(self, data, labels):
        """Run one train step. data/labels: NDArray or list of NDArrays
        (global batch; sharded onto the mesh's data axes here — batches
        already staged by dataflow.prefetch_to_mesh skip the transfer).
        Dispatch is asynchronous: the returned loss is lazy, and with
        telemetry/diagnostics/nan_sentinel disabled this path performs no
        host fence and no scalar device transfers. The
        `trainer_async_fence_every` knob adds a periodic host fence
        (every N steps) to bound dispatch run-ahead."""
        fence_every = _config.get("trainer_async_fence_every")
        return self._step_impl(data, labels, fence_every)

    def step_async(self, data, labels):
        """`step` minus the periodic fence: pure async dispatch returning
        a lazy loss handle. Nothing blocks until an explicit
        `.asscalar()`/`.item()`/`asnumpy()` on the handle (or telemetry/
        nan_sentinel, which document that they fence). Use with
        `dataflow.prefetch_to_mesh` so neither H2D transfer nor host
        bookkeeping sits between consecutive device steps."""
        return self._step_impl(data, labels, 0)

    def set_grad_accum(self, accum):
        """Set the gradient-accumulation factor: the jitted step splits
        the global batch into `accum` equal microbatches (lax.scan),
        accumulating gradients, so activation memory scales with the
        MICRObatch while loss/grads match the unsplit step up to
        reduction order. Every batch/label leading dim must divide by
        `accum` (validated at the next build). The mx.memsafe
        oom_recover=auto ladder drives this automatically."""
        accum = int(accum)
        if accum < 1:
            raise ValueError(f"grad accumulation factor must be >= 1, "
                             f"got {accum}")
        self._accum = accum
        self._step_cache.clear()
        return self

    def set_zero(self, on=True):
        """Toggle mx.zero optimizer-state sharding on a LIVE trainer:
        the resident moments (and fused-LAMB flat master) re-place into
        the sharded layout across the mesh's free data axes, and the
        next step re-jits with the reduce-scatter -> per-shard update ->
        all-gather wiring (off: everything moves back to the parameter's
        own sharding and the classic psum step). Values are bit-identical
        either way — only the layout moves. The mx.memsafe
        oom_recover=auto ladder drives this as the rung between
        remat='full' and gradient accumulation; zero='auto'/'on' does it
        at construction. Raises ValueError when nothing can shard."""
        if not self._ready:
            raise RuntimeError(
                "set_zero needs materialized parameters — run one step "
                "(or construct with explicit shapes) first")
        on = bool(on)
        if on == bool(self._zero):
            return self
        if on:
            _zero.enable()     # arm the module for the re-jitted step
            if self._fused:
                spec = _zero.flat_spec(self._fl, self.mesh)
                if spec is None:
                    raise ValueError(
                        "mx.zero: the fused-LAMB flat layout cannot "
                        "shard on this mesh (no data axis spans >1 "
                        "device, or rows do not divide)")
                self._zero_flat = spec
                self.params = jax.device_put(self.params, spec)
                self.opt_state = tuple(jax.device_put(z, spec)
                                       for z in self.opt_state)
            else:
                specs = _zero.plan_state(self.params, self._pshard,
                                         self.opt_state, self.mesh)
                if not any(s is not None for s in specs):
                    raise ValueError(
                        "mx.zero: no optimizer-state buffer can shard on "
                        "this mesh (no free data axis spans >1 device, "
                        "or everything is under zero_min_size)")
                self._zero_specs = specs
                self.opt_state = [
                    tuple(jax.device_put(z, zs or s) for z in st)
                    for st, zs, s in zip(self.opt_state, specs,
                                         self._pshard)]
            self._zero = True
        else:
            if self._fused:
                self.params = jax.device_put(self.params, self._rep)
                self.opt_state = tuple(jax.device_put(z, self._rep)
                                       for z in self.opt_state)
                self._zero_flat = None
            else:
                self.opt_state = [
                    tuple(jax.device_put(z, s) for z in st)
                    for st, s in zip(self.opt_state, self._pshard)]
                self._zero_specs = None
            self._zero = False
        self._step_cache.clear()
        self._refresh_comm_estimates()
        return self

    def _lr_cache_key(self):
        """The step-cache component for everything the in-jit lr bakes
        into the executable: None when lr is a traced argument (host
        fallback — nothing baked), the current lr for constant schedules,
        or the built-in scheduler's hyperparameter values. Mid-run
        mutation (set_learning_rate, editing scheduler fields) then
        re-jits warm instead of silently training at the stale schedule;
        the eviction in _step_impl bounds the cache at one entry per
        shape."""
        if not self._lr_inside:
            return None
        sch = self._opt.lr_scheduler
        if sch is None:
            return float(self._opt.lr)
        return (type(sch).__name__,) + tuple(
            (k, tuple(v) if isinstance(v, (list, tuple)) else v)
            for k, v in sorted(vars(sch).items())
            if isinstance(v, (int, float, str, list, tuple)))

    def _step_impl(self, data, labels, fence_every):
        try:
            return self._step_once(data, labels, fence_every)
        except Exception as e:  # noqa: BLE001 — classified below
            # mx.memsafe graceful OOM degradation: RESOURCE_EXHAUSTED and
            # pre-flight MemoryBudgetError walk the ladder under
            # oom_recover=auto. Disabled (default): one module-bool read
            # on an already-failing path, then re-raise — nothing on the
            # success hot path at all (zero-cost try in py3.11+)
            if not _memsafe._enabled or not _memsafe.is_oom(e):
                raise
            return _memsafe.recover_trainer(self, e, data, labels,
                                            fence_every)

    def _step_once(self, data, labels, fence_every):
        data = data if isinstance(data, (list, tuple)) else [data]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        if not self._ready:
            with jax.default_device(jax.devices()[0]):
                prev = _engine.set_recording(False)
                try:
                    self.block(*data)  # eager probe resolves deferred shapes
                finally:
                    _engine.set_recording(prev)
            self._setup()
        batch = [b._data if isinstance(b, NDArray) else jnp.asarray(b)
                 for b in list(data) + list(labels)]
        shapes = tuple(b.shape for b in batch)
        # memsafe extras in the key: the grad-accum factor, the block's
        # remat epoch (bumped by every remat() call — one int attr read,
        # so a mid-run policy change re-jits with memsafe off too), and
        # (enabled only — the disabled path adds no block walk) the
        # effective policy string, so a ladder escalation or a knob-driven
        # default change can never reuse the pre-escalation executable
        pol = _memsafe.policy_marker(self.block) if _memsafe._enabled \
            else None
        key = (len(data), len(labels), shapes, self._lr_cache_key(),
               self._accum, getattr(self.block, "_remat_epoch", 0), pol)
        is_miss = key not in self._step_cache
        # committed only AFTER the jitted call returns, so a trace-time
        # error or failed dispatch can't desync the host counter from the
        # device-resident _t_dev (which only advances on a completed call)
        step_no = self.num_update + 1
        # per-step config read (sub-µs vs a ms-scale step) so
        # mx.config.set("nan_sentinel", ...) takes effect mid-run
        sentinel = _config.get("nan_sentinel")
        # mx.trace: decided up front so an unsampled step pays nothing
        # beyond the module bool + one modulo (disabled: the bool alone).
        # A cache-miss step traces regardless of sampling — compiles are
        # always-record events (rare, seconds-scale)
        tracing = _trace._enabled and (is_miss or _trace.sampled(step_no))
        # mx.goodput accounts every completed step (replay-aware) — one
        # module bool here, like the other observers
        accounting = _goodput._enabled
        observing = (_telemetry._enabled or _diagnostics._enabled or sentinel
                     or _inspect._enabled or tracing or accounting)
        t_build = time.perf_counter() if (is_miss and observing) else None
        if is_miss:
            self._step_cache[key] = self._build_step(len(data), len(labels), shapes)
        if is_miss:
            # entries from a previous remat epoch are dead for EVERY shape
            # (remat() bumped the epoch exactly so they never run again):
            # evict them or each mid-run policy change leaks one compiled
            # executable per cached shape
            for k in [k for k in self._step_cache if k[5] != key[5]]:
                del self._step_cache[k]
        if is_miss and key[3] is not None:
            # in-jit-lr executables are keyed on the schedule's values:
            # evict the stale entry so set_learning_rate / scheduler-edit
            # loops don't accumulate one dead executable per value
            for k in [k for k in self._step_cache
                      if k[:3] == key[:3] and k[4:] == key[4:]
                      and k[3] != key[3]]:
                del self._step_cache[k]
        if _resilience._enabled:
            # the `oom@step:N` injection fires here — BEFORE any transfer
            # or dispatch, like a pre-flight rejection, so the donated
            # train state is intact and every degradation-ladder rung is
            # drivable in tests
            _resilience.fault_point("dispatch", step=step_no)
        if _guard._enabled:
            # mx.guard liveness: beat the dispatch (rate-limited file
            # write) and suspend the collective deadline across a cold
            # executable build — a minutes-scale first compile is a
            # legitimate non-step region, not a dead peer
            _guard.step_begin(step_no, compiling=is_miss)
        scalars = ()
        lr_host = None
        if not self._lr_inside:
            # untraceable (custom) schedule: lr stays host-computed, one
            # scalar transfer per step — the documented fallback. Computed
            # ONCE (a custom scheduler may be stateful; the diagnostics
            # record below reuses this value rather than re-invoking it)
            lr_host = self.fopt.lr_at(step_no)
            scalars = (jnp.asarray(lr_host, jnp.float32),)
        shardings = self._batch_shardings(len(data), len(labels), shapes)
        # prefetch_to_mesh already staged these: an array whose sharding
        # matches the target skips device_put entirely (no transfer, no
        # new buffer) — that is the zero-copy hot path ci sanity asserts
        batch = [b if getattr(b, "sharding", None) == s
                 else jax.device_put(b, s)
                 for b, s in zip(batch, shardings)]
        lint_traced = None
        if is_miss and _check._enabled:
            # mx.check graph lint for the fresh step executable, BEFORE
            # its first dispatch (trace-only — no compile, no transfer;
            # the global RNG key is read without advancing the stream):
            # donation misses, baked constants, dtype promotions,
            # degenerate sharding, retrace hazards. The trace is handed
            # to memsafe's preflight below so check+memsafe together
            # cost ONE trace per miss, not two
            lint_args = (self.params, self.aux, self.opt_state,
                         self._t_dev) + scalars \
                + (_random.get_state(),) + tuple(batch)
            if _memsafe._enabled:
                lint_traced = _check.trace_jit(self._step_cache[key],
                                               lint_args)
            try:
                _check.check_step(self, key, self._step_cache[key],
                                  lint_args, batch=batch,
                                  traced=lint_traced)
            except _check.CheckError:
                # check=error: the rejected executable must not stay
                # cached — a retried same-shape call would skip the lint
                del self._step_cache[key]
                raise
        # StepTraceAnnotation: jax.profiler device traces group work by
        # train step (the reference profiler's per-iteration ranges —
        # SURVEY §5.1); free when no trace is active
        t_step = time.perf_counter() if observing else None
        in_scope = _diagnostics._enabled
        if in_scope:
            # the watchdog names this scope when the step never completes:
            # with >1 reducing device a hang here is almost always the
            # gradient psum waiting on a straggler/dead rank
            _diagnostics._scope_begin(
                "sharded_step(psum)" if self._tele_reduce_bytes
                else "sharded_step(dispatch)", step_no)
        prefl = None
        try:
            rngk = _random.next_key()
            if is_miss and _memsafe._enabled:
                # pre-flight budget check for the fresh executable, BEFORE
                # its first dispatch: AOT lower+compile (warm via
                # compile_cache_dir for the lazy first call below) and
                # compare execution peak + resident state/batch against
                # device capacity. A predicted overrun raises
                # MemoryBudgetError with everything intact — the
                # oom_recover=auto ladder (or the caller) re-plans
                try:
                    prefl = _memsafe.preflight_step(
                        self, key, self._step_cache[key],
                        (self.params, self.aux, self.opt_state,
                         self._t_dev) + scalars + (rngk,) + tuple(batch),
                        traced=lint_traced)
                except _memsafe.MemoryBudgetError:
                    # a rejected executable must not stay cached: a
                    # retried same-shape call would hit the cache and
                    # dispatch past the check
                    del self._step_cache[key]
                    raise
            # sampled steps also carry an mx.trace annotation so the XLA
            # device trace groups this step's kernels under the same
            # (rank, step) tag as the host spans
            ann = _trace.annotate(step_no) if tracing else _NULLCTX
            with jax.profiler.StepTraceAnnotation("train_step",
                                                  step_num=step_no), ann:
                loss, self.params, self.aux, self.opt_state, self._t_dev = \
                    self._step_cache[key](
                        self.params, self.aux, self.opt_state, self._t_dev,
                        *scalars, rngk, *batch)
            t_disp = time.perf_counter() if tracing else None
            self.num_update = step_no
            fenced = False
            if observing:
                if _telemetry._enabled or sentinel or _inspect._enabled \
                        or tracing or accounting:
                    # fence on the loss (one output of the step executable
                    # fences the whole executable) so the histogram records
                    # device step time, not just async dispatch; on tunnel
                    # platforms where block_until_ready is a no-op this
                    # degrades to dispatch time. Diagnostics-only mode
                    # skips the fence — a ring append must not cost the
                    # host/device overlap — so its records mean "step
                    # dispatched" there. Inspect fences too: its step time
                    # is the MFU denominator and must be device time
                    jax.block_until_ready(loss)
                    fenced = True
                t_done = time.perf_counter()
                if _telemetry._enabled:
                    self._tele_record_step(batch, t_build, t_step)
                if _diagnostics._enabled or sentinel:
                    self._diag_record_step(
                        loss,
                        lr_host if lr_host is not None
                        else self.fopt.lr_at(self.num_update),
                        shapes, t_build, sentinel)
                if tracing:
                    self._trace_record_step(step_no, t_build, t_step,
                                            t_disp, t_done)
                if accounting:
                    # before inspect (whose miss-path analysis takes
                    # real wall time): the step's interval must end at
                    # the fence, not at the analyzer
                    _goodput.note_step(step_no, t_build, t_step, t_done)
                if _inspect._enabled:
                    # LAST observer: the miss-path analysis lower+compile
                    # takes real wall time that must not leak into the
                    # compile_seconds / ring compile records above. When
                    # the memsafe preflight already analyzed this
                    # executable and handed it to inspect, skip the
                    # duplicate compile
                    self._inspect_record_step(
                        key, scalars, rngk, batch, t_build, t_step, t_done,
                        prerecorded=bool(prefl
                                         and prefl.get("inspect_recorded")))
            if not fenced and fence_every \
                    and self.num_update % int(fence_every) == 0:
                # bound async run-ahead: without an observer fencing for
                # us (diagnostics-only mode included), the host could
                # otherwise queue unbounded steps (and their batch
                # buffers) ahead of the device
                jax.block_until_ready(loss)
        finally:
            if in_scope:
                _diagnostics._scope_end()
        if _resilience._enabled:
            # periodic verified checkpoint, fault injection, and the
            # graceful-preemption final save + EXIT_PREEMPTED — all behind
            # one module-bool check on the disabled fast path
            _resilience.on_step(self)
        if _guard._enabled:
            # mx.guard: completed-step heartbeat (feeds the supervisor's
            # staleness clock AND re-arms the collective deadline), then
            # the SDC digest vote on its sdc_check_every cadence — after
            # resilience so a just-injected corrupt_grad is caught by
            # the vote this same boundary
            _guard.on_step(self, step_no)
        if _scope._enabled:
            # mx.scope live introspection: stamp the completed step for
            # /healthz + /statusz and drive an armed /profilez device
            # capture at this boundary, on this thread — the capture
            # start/stop must never race a dispatching step
            _scope.on_step(self, step_no)
        return NDArray(loss)

    def _trace_record_step(self, step_no, t_build, t_step, t_disp, t_done):
        """mx.trace spans for one SAMPLED step: host dispatch
        (t_step→t_disp) and the fence (t_disp→t_done — device-time share
        on backends where block_until_ready actually blocks; tracing
        forces the fence exactly so this span means device time, the same
        trade telemetry makes), plus the skew-probe tick at the
        collective boundary. A cache-miss step records ONE compile span
        (build through fenced first call) instead — its dispatch is
        compile-dominated and would poison the step category the verdict
        sums, the same exclusion the telemetry step histogram makes."""
        if t_build is not None:
            _trace.record_span("step.compile", t_build, t_done,
                               step=step_no, cat="compile", always=True,
                               block=type(self.block).__name__)
        else:
            _trace.record_span("step.dispatch", t_step, t_disp,
                               step=step_no, cat="step")
            _trace.record_span("step.fence", t_disp, t_done, step=step_no,
                               cat="step")
        _trace.skew_tick(step_no)

    def _diag_record_step(self, loss, lr, shapes, t_build, sentinel):
        """Flight-recorder entry for one sharded step; with the
        nan_sentinel knob on (works with diagnostics off too — the dump
        then just has an empty ring), the loss is host-fetched and
        checked here; NonFiniteError propagates after the post-mortem."""
        if t_build is not None:
            _diagnostics.record_event(
                "compile",
                block=f"ShardedTrainer({type(self.block).__name__})",
                compile_time_s=round(time.perf_counter() - t_build, 6),
                step=self.num_update)
        loss_val = _diagnostics._scalar(loss) if sentinel else None
        _diagnostics.record_step(
            self.num_update, loss=loss_val, lr=float(lr), shapes=shapes,
            trainer="ShardedTrainer", compiled=t_build is not None)
        if sentinel:
            # checked AFTER recording so the fatal step — non-finite loss
            # included — is the ring's last entry in the post-mortem
            _diagnostics.sentinel_check(loss_val, "loss", self.num_update)

    def _inspect_record_step(self, key, scalars, rngk, batch, t_build,
                             t_step, t_done, prerecorded=False):
        """Cost attribution for one sharded step. On a step-cache miss the
        freshly built executable is lowered+compiled once more for XLA
        cost/memory analysis (warm via the persistent cache when
        compile_cache_dir is set; the post-call state has the same avals
        and shardings the executed call had, donation included). On a warm
        step the fenced dispatch→fence window [t_step, t_done] feeds the
        executable's MFU denominator — compile steps are excluded, like
        the telemetry histogram, and so is the other observers' own
        recording overhead (t_done is stamped right after the fence)."""
        name = f"ShardedTrainer({type(self.block).__name__})"
        ikey = _inspect.key_repr(key)
        if t_build is not None:
            if not prerecorded:
                _inspect.analyze_jit(
                    name, ikey, self._step_cache[key], self.params,
                    self.aux, self.opt_state, self._t_dev, *scalars, rngk,
                    *batch, collectives=self._coll_est)
        elif t_step is not None:
            _inspect.note_step(name, ikey, t_done - t_step)

    def _tele_record_step(self, batch, t_build, t_step):
        """Telemetry for one sharded step: compile accounting on a
        step-cache miss (with a signature diff explaining the re-jit),
        step latency, and gradient-reduction collective bytes. The jitted
        call compiles lazily on its first invocation, so t_build brackets
        build + first call."""
        now = time.perf_counter()
        if t_step is not None and t_build is None:
            # compile steps are excluded: the lazy first invocation would
            # put a seconds-long compile into the step histogram and poison
            # p99 / the input-stall denominator (it lands in compile_seconds)
            _M_STEP_SECONDS.observe(now - t_step)
            _telemetry.event("step", dur_s=round(now - t_step, 6),
                             step=self.num_update)
        if t_build is not None:
            dt = now - t_build
            _M_COMPILES.inc()
            _M_COMPILE_SECONDS.observe(dt)
            sig = _telemetry.signature(batch)
            causes, changed = _telemetry.diff_signature(self._tele_sig, sig)
            kind = "compile" if self._tele_sig is None else "recompile"
            if self._tele_sig is not None:
                _M_RECOMPILES.inc()
            self._tele_sig = sig
            _telemetry.event(
                kind, block=f"ShardedTrainer({type(self.block).__name__})",
                compile_time_s=round(dt, 6), causes=causes, changed=changed,
                signature=sig)
        for op, nbytes in self._tele_coll.items():
            _M_COLL_CALLS.labels(op=op).inc()
            _M_COLL_BYTES.labels(op=op).inc(nbytes)

    # ------------------------------------------------------------------
    def sync_to_block(self):
        """Write device state back into the gluon Parameters (checkpointing)."""
        params = self._fl.unflatten(self.params) if self._fused else self.params
        for (_, p), v in zip(self._grad_params, params):
            p.data()._data = v
        for (_, p), v in zip(self._aux_params, self.aux):
            p.data()._data = v

    def save_checkpoint(self, prefix):
        self.sync_to_block()
        self.block.save_parameters(prefix + ".params")

    # -- sharded checkpoint/resume (reference: Module.save_checkpoint +
    #    save_optimizer_states; here orbax writes each shard from the host
    #    that owns it, the TPU answer to dmlc::Stream .params files) ------
    def _state_pytree(self):
        """The checkpointed state, used by BOTH save and restore so the
        two can never drift apart."""
        if self._fused:
            # canonical per-tensor layout so fused-LAMB checkpoints stay
            # portable across param modes (f32: master precision preserved)
            m = self._fl.unflatten_master(self.opt_state[0])
            v = self._fl.unflatten_master(self.opt_state[1])
            return {
                "params": self._fl.unflatten_master(self.params),
                "aux": list(self.aux),
                "opt_state": [[mi, vi] for mi, vi in zip(m, v)],
                "num_update": jnp.asarray(self.num_update),
            }
        return {
            "params": list(self.params),
            "aux": list(self.aux),
            "opt_state": [list(st) for st in self.opt_state],
            "num_update": jnp.asarray(self.num_update),
        }

    def save_states(self, directory):
        """Write params + optimizer state + step count + the global RNG
        stream as an orbax sharded checkpoint (works multi-host: each
        process writes only its local shards)."""
        _ckpt_save(self, directory)

    def load_states(self, directory, reshard=None):
        """Restore a save_states() checkpoint onto the current mesh. A
        checkpoint written on a DIFFERENT topology (mesh shape or param
        mode) is redistributed bit-exactly while `reshard` allows it:
        None reads the `reshard` knob (default 'auto'), 'auto'/'host'
        redistribute, 'off' raises MeshMismatchError on any mismatch."""
        state = _ckpt_restore(self, directory, reshard)
        if self._fused:
            # a zero'd trainer re-flattens into its SHARDED resident
            # layout (checkpoints stay canonical per-tensor either way)
            pspec = self._zero_flat if self._zero else self._rep
            self.params = jax.device_put(
                self._fl.flatten(state["params"]), pspec)
            mdt = self._fl.moments_dtype
            self.opt_state = (
                jax.device_put(self._fl.flatten(
                    [st[0] for st in state["opt_state"]], mdt), pspec),
                jax.device_put(self._fl.flatten(
                    [st[1] for st in state["opt_state"]], mdt), pspec))
        else:
            self.params = list(state["params"])
            self.opt_state = [tuple(st) for st in state["opt_state"]]
        self.aux = list(state["aux"])
        self.num_update = int(state["num_update"])
        # re-seed the device-resident step counter from the restored count
        self._t_dev = jax.device_put(
            jnp.asarray(self.num_update, jnp.int32), self._rep)

    def predict_step_bytes(self, data, labels):
        """AOT memory plan for one train step at these batch SHAPES — no
        device step executes, no batch transfers: the step is built and
        lowered against ShapeDtypeStruct avals for the batch (host numpy /
        NDArray / jax arrays all work, only shape+dtype are read), compiled
        analytically, and XLA's memory_analysis is combined with the
        resident train-state bytes. Returns {"exec_peak_bytes",
        "resident_bytes", "predicted_bytes", "capacity_bytes",
        "headroom_bytes", "fits"} (exec_peak None when the backend
        withholds it; capacity/headroom/fits None when no capacity is
        known). This is what dataflow.autofit binary-searches over."""
        data = data if isinstance(data, (list, tuple)) else [data]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        if not self._ready:
            raise RuntimeError(
                "predict_step_bytes needs materialized parameters — run "
                "one step (or use explicit shapes) before planning")

        def aval(b):
            raw = b._data if isinstance(b, NDArray) else b
            return jax.ShapeDtypeStruct(tuple(raw.shape), raw.dtype)

        batch = [aval(b) for b in list(data) + list(labels)]
        shapes = tuple(b.shape for b in batch)
        jitted = self._build_step(len(data), len(labels), shapes)
        scalars = () if self._lr_inside else (
            jax.ShapeDtypeStruct((), jnp.float32),)
        # the global key is a concrete array already on device — passing
        # it to lower() reads its aval only, and unlike next_key() it does
        # not advance the training RNG stream
        rng = _random.get_state()
        args = (self.params, self.aux, self.opt_state, self._t_dev) \
            + scalars + (rng,) + tuple(batch)
        exec_peak, _compiled, err = _memsafe._analyze(jitted, args)
        resident = _memsafe.resident_bytes(
            (self.params, self.aux, self.opt_state)) \
            + sum(int(math.prod(s.shape)) * s.dtype.itemsize for s in batch)
        capacity = _memsafe.capacity_bytes()
        predicted = int(resident) + int(exec_peak or 0)
        out = {
            "exec_peak_bytes": exec_peak,
            "resident_bytes": int(resident),
            "predicted_bytes": predicted,
            "capacity_bytes": capacity,
            "headroom_bytes": None if capacity is None
            else int(capacity) - predicted,
            "fits": None if capacity is None else predicted <= capacity,
        }
        if err is not None:
            out["analysis_error"] = err
        return out

    @property
    def param_count(self):
        if self._fused:
            return sum(self._fl.sizes)
        return sum(int(jnp.size(p)) for p in self.params)


# -- shared checkpoint plumbing (ShardedTrainer + pipeline trainers) -------


def _orbax_write(trainer, directory):
    """Orbax save of the trainer's _state_pytree PLUS the global RNG
    stream, so a resumed run replays the same dropout/shuffle draws
    (trajectory-exact resume)."""
    import os

    import orbax.checkpoint as ocp

    from .. import random as _random

    state = trainer._state_pytree()
    state["rng_key"] = jax.random.key_data(_random.get_state())
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(os.path.join(str(directory), "state")),
               state, force=True)
    ckptr.wait_until_finished()


def _ckpt_save(trainer, directory):
    """Write one trainer checkpoint. With mx.resilience enabled the write
    is atomic and verified: state lands in a temp directory, a
    manifest.json with per-file checksums + step + mesh fingerprint is
    fsynced next to it, and the whole directory renames into place — a
    kill mid-save can never leave a checkpoint that restore would trust.
    Disabled (the default) keeps the plain orbax write: no temp copy, no
    hashing, byte-for-byte the old behavior."""
    if not _resilience._enabled:
        _orbax_write(trainer, directory)
        return
    from . import reshard as _reshard
    _resilience.write_checkpoint(
        directory, lambda tmp: _orbax_write(trainer, tmp),
        step=int(trainer.num_update),
        fingerprint=_resilience.trainer_fingerprint(trainer),
        layouts=_reshard.state_layouts(trainer))


def _ckpt_restore(trainer, directory, reshard=None):
    """Restore + re-seed the global RNG. Returns the state pytree for the
    trainer to apply its fields from. With mx.resilience enabled and a
    manifest present, checksums are verified first (raising
    CheckpointCorruptError on a torn/corrupt checkpoint) and the mesh/
    param-mode fingerprint is compared: a topology change is REDISTRIBUTED
    onto the current mesh while the `reshard` policy allows it (the knob,
    or the explicit load_states(reshard=...) argument) — planned from the
    manifest's recorded per-array shardings, executed by orbax reading
    each target shard's byte range from disk (peak memory bounded per
    array, no device all-gather), recorded in reshard telemetry and the
    post-mortem resume section. With reshard='off' the mismatch raises
    MeshMismatchError naming both fingerprints."""
    import os

    import orbax.checkpoint as ocp

    from .. import random as _random

    plan = None
    manifest = None
    t0 = time.perf_counter()
    if _resilience._enabled and os.path.exists(
            os.path.join(str(directory), "manifest.json")):
        manifest = _resilience.verify_checkpoint(directory)
        if _resilience.reshard_gate(manifest, trainer, str(directory),
                                    reshard):
            from . import reshard as _reshard
            plan = _reshard.plan_restore(manifest, trainer)
    target = trainer._state_pytree()
    target["rng_key"] = jax.random.key_data(_random.get_state())
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(
        os.path.abspath(os.path.join(str(directory), "state")), target)
    if plan is not None:
        from . import reshard as _reshard
        _reshard.note_reshard(
            "restore", plan, time.perf_counter() - t0,
            src_fp=manifest.get("fingerprint"),
            dst_fp=_resilience.trainer_fingerprint(trainer))
    _random.set_state(state["rng_key"])
    return state


class PipelineCheckpointMixin:
    """save_states/load_states for the pipeline trainers: their state is a
    flat param list + per-param opt-state tuples + the step count (no aux
    — BatchNorm stats inside pipeline stages raise at construction)."""

    def _state_pytree(self):
        return {
            "params": list(self.params),
            "opt_state": [list(st) for st in self.opt_state],
            "num_update": jnp.asarray(self.num_update),
        }

    def _ensure_setup(self):
        # the hetero PipelineTrainer defers _setup() to its first step (to
        # resolve deferred param shapes from a probe batch); restoring into
        # a FRESH trainer must materialize params first. Works only when
        # every stage block has explicit shapes — deferred-shape stages
        # need one step before load_states.
        if not getattr(self, "_ready", True) and not hasattr(self, "params"):
            self._setup()
            self._ready = True

    def save_states(self, directory):
        _ckpt_save(self, directory)

    def load_states(self, directory, reshard=None):
        self._ensure_setup()
        state = _ckpt_restore(self, directory, reshard)
        self.params = list(state["params"])
        self.opt_state = [tuple(st) for st in state["opt_state"]]
        self.num_update = int(state["num_update"])
