"""Distribution layer: mesh + sharding + sharded training + seq/pipe parallel.

See SURVEY.md §2.4/§2.5 — this package is the TPU-native replacement for the
reference's KVStore transports and the home of the net-new parallelism the
reference lacks (tensor, pipeline, sequence/ring)."""
from .mesh import (make_mesh, MeshPlan, current_mesh, set_mesh, named_sharding,
                   PartitionSpec, local_mesh_devices, manual_axes, in_manual)
from . import specs
from .specs import batch_spec, param_spec, fsdp_spec, replicated, apply_tp_rules
from .functional_opt import FunctionalOptimizer
from .trainer import ShardedTrainer
from .ring_attention import (ring_attention, ring_self_attention,
                             sp_self_attention)
from .pipeline import (pipeline_apply, pipeline_shard_map,
                       pipeline_apply_hetero, PipelineTrainer,
                       SeqPipelineTrainer)
from .distributed import init_distributed, is_distributed
from .elastic import AutoCheckpoint, resize_trainer
from . import reshard
from . import zero
from .ulysses import ulysses_attention, ulysses_self_attention
from .moe import moe_apply, moe_ffn

__all__ = ["make_mesh", "MeshPlan", "current_mesh", "set_mesh", "named_sharding",
           "PartitionSpec", "local_mesh_devices", "specs", "batch_spec",
           "param_spec", "fsdp_spec", "replicated", "apply_tp_rules",
           "FunctionalOptimizer", "ShardedTrainer", "ring_attention",
           "ring_self_attention", "sp_self_attention", "manual_axes",
           "in_manual", "pipeline_apply", "pipeline_shard_map",
           "pipeline_apply_hetero", "PipelineTrainer", "SeqPipelineTrainer",
           "init_distributed",
           "is_distributed", "ulysses_attention", "ulysses_self_attention",
           "moe_apply", "moe_ffn", "AutoCheckpoint", "resize_trainer",
           "reshard", "zero"]
