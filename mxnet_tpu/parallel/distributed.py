"""Multi-host initialization (reference: ps-lite Postoffice::Start +
`tools/launch.py` env wiring — here it is one jax.distributed handshake).

`tools/launch.py` spawns one process per host with JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID set; `init_distributed()` reads them and
brings the process into the global SPMD job. After it returns,
`jax.devices()` spans every host and a `parallel.make_mesh()` covers the
full ICI/DCN topology — collectives ride the fabric with no further setup.
"""
from __future__ import annotations

import os

__all__ = ["init_distributed", "rank", "num_workers", "is_distributed"]

_initialized = False


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Join the multi-host job described by the launcher env (no-op for
    single-process runs)."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return  # single-host run; nothing to do
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("JAX_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    import jax
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # CPU multi-process collectives need an explicit transport; gloo is
        # compiled into stock jaxlib (used for the launcher test harness —
        # the reference's "multi-node as multi-process on localhost"
        # pattern, SURVEY §4)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def is_distributed():
    return _initialized


def rank():
    import jax
    return jax.process_index()


def num_workers():
    import jax
    return jax.process_count()
