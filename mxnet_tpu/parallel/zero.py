"""mx.zero — cross-replica optimizer-state sharding.

Every data-parallel replica of a ShardedTrainer holds a full copy of the
optimizer moments (and, on the fused-LAMB path, the fp32 flat master) —
the single largest avoidable slice of device memory, and the one
mx.check's degenerate-sharding rule flags. Grounding (PAPERS.md):
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336) — replace the gradient all-reduce +
replicated weight update with

    reduce-scatter(grad)  ->  per-shard weight update  ->  all-gather(w')

Each replica then updates only 1/D of the parameters (D = the data-axis
extent) and KEEPS only 1/D of the optimizer state resident. Collective
payload is unchanged — a ring all-reduce moves 2(D-1)/D of the gradient,
the reduce-scatter + all-gather pair moves (D-1)/D each — but the
update's FLOPs/HBM traffic drop by D and the resident optimizer bytes by
(D-1)/D. With Adam (8 bytes/param of moments) at D=8 that is 7 bytes/
param back; with fused LAMB (4 master + 8 moment bytes/param, all
sharded here) it is 10.5 bytes/param.

Everything is expressed INSIDE the trainer's single jitted step as
sharding annotations (in/out shardings on the optimizer state plus
`with_sharding_constraint` on the gradient / updated param), so XLA's
SPMD partitioner emits the reduce-scatter/all-gather itself and its
latency-hiding scheduler can overlap the all-gather with the tail of
backward. Donation is preserved: the sharded state is donated with the
same sharding it returns with, so mx.check's donation lint stays quiet
on a zero'd step.

The `zero` knob: 'off' (default) is the zero-overhead fast path — the
trainer makes no call into this module beyond one construction-time
config read (ci/run.sh sanity asserts it). 'auto' shards at trainer
construction whenever the mesh's data axes span more than one device
(a no-op otherwise). 'on' insists: construction raises when nothing can
be sharded (no data axis > 1, or no optimizer state clears
`zero_min_size`). Independent of the knob, the mx.memsafe
oom_recover=auto ladder may enable sharding on a live trainer
(`trainer.set_zero(True)`) as the recovery rung between remat=full and
gradient accumulation.

Sharding rules (see `zero_spec`): the optimizer state of a parameter
shards over the data axes NOT already present in the parameter's own
sharding — all of (dp, fsdp) in replicate mode, the dp remainder for an
fsdp-sharded parameter. The fused-LAMB flat master/moment vectors shard
on their single dimension whenever the (rows, chunk) layout divides.
Parameters whose state cannot shard (no divisible dim, or smaller than
`zero_min_size` elements) keep the classic psum path — the step mixes
both per parameter.
"""
from __future__ import annotations

import numpy as np

from jax.sharding import NamedSharding, PartitionSpec

from .. import config as _config
from . import specs as _specs

__all__ = [
    "enable", "disable", "enabled", "maybe_enable",
    "data_extent", "zero_axes", "zero_spec", "flat_spec", "plan_state",
    "eligible", "constrain",
]

_enabled = False              # the fast-path bool; hook sites read it directly


def enabled():
    """True when mx.zero is armed (the trainer reads the module global
    `_enabled` directly — this accessor is the public spelling)."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def maybe_enable():
    """Arm iff the `zero` knob asks ('auto' or 'on'). Called at trainer
    construction — one config read, never on the step hot path."""
    if _enabled:
        return True
    if _config.get("zero") != "off":
        enable()
    return _enabled


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

def data_extent(mesh):
    """Product of the data-axis sizes — the D in the (D-1)/D memory win."""
    return int(mesh.shape.get("dp", 1)) * int(mesh.shape.get("fsdp", 1))


def _spec_entries(sharding, ndim):
    """The PartitionSpec entries of a sharding, padded to ndim."""
    spec = getattr(sharding, "spec", sharding)
    entries = list(tuple(spec or ()))
    return entries + [None] * (ndim - len(entries))


def zero_axes(mesh, sharding, ndim):
    """Data axes (size > 1) NOT already used by `sharding` — the axes the
    optimizer state can additionally shard over. Replicated params yield
    all sharded data axes; an fsdp-sharded param yields the dp remainder."""
    used = set()
    for entry in _spec_entries(sharding, ndim):
        if entry is None:
            continue
        used.update(entry if isinstance(entry, tuple) else (entry,))
    return tuple(a for a in _specs.DATA_AXES
                 if a not in used and int(mesh.shape.get(a, 1)) > 1)


def _min_size():
    return int(_config.get("zero_min_size"))


def zero_spec(shape, base_sharding, mesh):
    """The zero sharding for one parameter's optimizer state (and the
    per-shard view of its weight update): the parameter's own sharding
    plus the free data axes on the largest still-unsharded dim that
    divides by their extent. None when nothing shards — no free data
    axis, no divisible dim, or fewer than `zero_min_size` elements (tiny
    LayerNorm/bias state is not worth the reshard churn, same argument
    as fsdp_min_size)."""
    shape = tuple(shape)
    if not shape or int(np.prod(shape)) < _min_size():
        return None
    axes = zero_axes(mesh, base_sharding, len(shape))
    if not axes:
        return None
    extent = int(np.prod([mesh.shape[a] for a in axes]))
    entries = _spec_entries(base_sharding, len(shape))
    for dim in sorted(range(len(shape)), key=lambda i: -shape[i]):
        if entries[dim] is not None:
            continue
        if shape[dim] % extent == 0 and shape[dim] >= extent:
            entries[dim] = axes if len(axes) > 1 else axes[0]
            return NamedSharding(mesh, PartitionSpec(*entries))
    return None


def flat_spec(fl, mesh):
    """The zero sharding for the fused-LAMB flat master/moment vectors,
    or None. The flat layout is (n_rows, CHUNK) underneath — the vector
    shards on dim 0 only when whole rows land on each device (n_rows
    divisible by the data extent), so the row-wise trust-ratio math in
    FusedLamb.apply_flat partitions cleanly."""
    axes = zero_axes(mesh, _specs.replicated(mesh), 1)
    if not axes:
        return None
    extent = int(np.prod([mesh.shape[a] for a in axes]))
    if fl.total < _min_size() or not fl.shardable_rows(extent):
        return None
    return NamedSharding(mesh, PartitionSpec(axes if len(axes) > 1
                                             else axes[0]))


def plan_state(params, pshards, states, mesh):
    """Per-parameter zero shardings for a trainer's optimizer state:
    one entry per param — a NamedSharding, or None for params that keep
    the classic psum path (no state to shard, too small, or no divisible
    dim). Aligned with `params`/`pshards`."""
    return [zero_spec(p.shape, s, mesh) if st else None
            for p, s, st in zip(params, pshards, states)]


def eligible(trainer):
    """True when `trainer` COULD shard optimizer state on its current
    mesh — what the mx.memsafe ladder checks before proposing the
    'enable mx.zero' rung. Requires a ready ShardedTrainer with a data
    axis spanning >1 device and at least one shardable state buffer."""
    if not getattr(trainer, "_ready", False) \
            or not hasattr(trainer, "set_zero"):
        return False
    mesh = getattr(trainer, "mesh", None)
    if mesh is None or data_extent(mesh) <= 1:
        return False
    if getattr(trainer, "_fused", False):
        return flat_spec(trainer._fl, mesh) is not None
    return any(s is not None for s in plan_state(
        trainer.params, trainer._pshard, trainer.opt_state, mesh))


# ---------------------------------------------------------------------------
# the in-step hook
# ---------------------------------------------------------------------------

def constrain(x, sharding):
    """`with_sharding_constraint` under a monkeypatchable name: the
    trainer's zero'd step routes every gradient reduce-scatter, per-shard
    slice and updated-param all-gather through here, so ci/run.sh sanity
    can assert the zero=off fast path makes ZERO of these calls."""
    import jax
    return jax.lax.with_sharding_constraint(x, sharding)
