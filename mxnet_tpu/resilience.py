"""mx.resilience — preemption-safe training: atomic verified checkpoints,
auto-resume, graceful SIGTERM handling, transient-fault retry, and a
fault-injection harness.

TPU pods are preemptible and multi-host: a production framework must
survive rank death, SIGTERM preemption, and torn/corrupt checkpoints.
The reference's KVStore/PS-Lite lineage treated worker failure as a
first-class event; this module is the TPU-native equivalent. Five pieces:

  * **atomic verified checkpoints** — every managed checkpoint is written
    to a temp directory, described by a `manifest.json` carrying per-file
    CRC32 checksums + the step id + a mesh/config fingerprint, fsynced,
    and atomically renamed into place. A kill mid-save leaves only a
    `*.tmp-*` directory that restore never considers. On restore the
    checksums are verified, a mesh/param-mode change is REDISTRIBUTED
    onto the current topology (parallel/reshard.py; bit-exact, planned
    from the manifest's recorded per-array shardings) while the
    `reshard` knob allows it — or rejected with `MeshMismatchError`
    when reshard='off' — and a torn/corrupt latest checkpoint falls
    back to the newest previous GOOD one.
  * **auto-resume** — the `resume` knob ("auto" or an explicit path) makes
    a fresh `ShardedTrainer` (and `Estimator.fit(resume=...)`) restore
    model/optimizer/RNG/device-step-counter from the newest verified
    checkpoint; already-consumed steps/epochs are skipped by the restored
    counters.
  * **graceful preemption** — `install()` registers a SIGTERM/SIGINT
    handler that only sets a flag (async-signal-safe); the trainer
    finishes the in-flight step, writes a final checkpoint, and exits
    with the distinct `EXIT_PREEMPTED` code so supervisors can tell
    "saved and evicted" from "crashed".
  * **RetryPolicy** — exponential backoff + jitter + retryable-exception
    classification, applied to transient faults: prefetch staging in
    `dataflow.prefetch_to_mesh`, silent DataLoader worker death
    (respawn + work re-enqueue), and checkpoint I/O.
  * **fault injection** — the `fault_inject` knob ("sigterm@step:5",
    "kill@step:3@rank:1", "corrupt_ckpt@step:4", "stall_input:250")
    drives deterministic failures through the SAME hooks production uses,
    so every recovery path is provable end-to-end (tests/unittest/
    test_resilience.py; `tools/launch.py --max-restarts` supervises the
    relaunch side).

Cost model: DISABLED (the default) is the production fast path — the
trainer hook is one module-bool check, no signal handlers are installed,
`save_states` writes exactly what it wrote before (no manifest, no
hashing), and restore verifies nothing (`ci/run.sh sanity` asserts
this). Enable with `mx.resilience.install()` / `MXNET_TPU_RESILIENCE=1`.
"""
from __future__ import annotations

import json
import os
import random as _pyrandom
import shutil
import signal as _signal
import sys
import threading
import time
import zlib

from . import _locklint
from . import config as _config
from . import diagnostics as _diagnostics
from . import goodput as _goodput
from . import guard as _guard
from . import telemetry as _telemetry
from . import trace as _trace

__all__ = [
    "enable", "disable", "enabled", "install", "uninstall", "preempted",
    "clear_preempted", "RetryPolicy", "retry_call", "CheckpointCorruptError",
    "MeshMismatchError", "PreemptedExit", "EXIT_PREEMPTED",
    "write_checkpoint", "verify_checkpoint", "list_checkpoints",
    "check_fingerprint", "trainer_fingerprint", "CheckpointManager",
    "manager_for", "FaultInjector", "fault_point", "restart_count",
    "last_resume", "note_preemption", "save_estimator", "restore_estimator",
    "EXIT_SHRINK", "EXIT_GROW", "reshard_gate", "request_shrink",
]

# distinct "preempted: state saved, exiting on request" process exit code —
# chosen outside the shell (126..128+N) and common-errno ranges so a
# supervisor (tools/launch.py, k8s) can classify it unambiguously
EXIT_PREEMPTED = 83
# elastic reshape requests (fault-injectable via shrink@step / grow@step;
# honored by tools/launch.py --elastic): state saved, exiting so the
# supervisor can relaunch the gang one worker smaller / larger
EXIT_SHRINK = 84
EXIT_GROW = 85

_lock = _locklint.make_rlock("resilience.state")
_enabled = False          # the fast-path bool: trainer hooks check ONLY this
_installed = False        # signal handlers chained
_prev_handlers = {}
_preempt = {"flag": False, "signum": None}
_injector = None          # FaultInjector parsed from the fault_inject knob
_resume_info = None       # {"path", "step", "fallbacks"} of the last restore
_pending_reshard = None   # staged by reshard.note_reshard for _note_resume

_M_SAVE_SECONDS = _telemetry.histogram(
    "checkpoint_save_seconds", "wall time of one managed checkpoint save "
    "(state write + manifest hash + atomic rename)")
_M_RESTORE_SECONDS = _telemetry.histogram(
    "checkpoint_restore_seconds", "wall time of one verified checkpoint "
    "restore (checksum verify + state load)")
_M_VERIFY_FAILURES = _telemetry.counter(
    "checkpoint_verify_failures_total", "checkpoints rejected at restore "
    "time (torn write, checksum mismatch, missing manifest entry) — each "
    "one fell back to an older checkpoint")
_M_RESTARTS = _telemetry.counter(
    "restarts_total", "supervised gang relaunches this process has been "
    "through (from MXNET_TPU_RESTART_COUNT, exported by tools/launch.py "
    "--max-restarts)")
_M_PREEMPTIONS = _telemetry.counter(
    "preemptions_total", "SIGTERM/SIGINT preemptions handled gracefully "
    "(final checkpoint written, exited EXIT_PREEMPTED)")
_M_RETRIES = _telemetry.counter(
    "retries_total", "transient-fault retries by site (label site=): "
    "prefetch staging, dataloader worker respawn, checkpoint I/O")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed verification (torn write / checksum mismatch /
    missing manifest or entry). Managed restores fall back to the newest
    previous good checkpoint instead of propagating this."""


class MeshMismatchError(RuntimeError):
    """A verified checkpoint was written for a different mesh/param-mode
    than the trainer restoring it, and the `reshard` knob is off (or the
    mismatch is not a topology at all — e.g. a different trainer class).
    With reshard='auto' (the default) a pure mesh/param-mode mismatch is
    redistributed via parallel/reshard.py instead of raising. Carries
    `.mismatch` ({key: (checkpoint, current)}) so callers can tell a
    reshardable topology change from a structural one."""

    def __init__(self, message, mismatch=None):
        super().__init__(message)
        self.mismatch = dict(mismatch or {})


class PreemptedExit(SystemExit):
    """SystemExit subclass raised after the final preemption checkpoint;
    carries EXIT_PREEMPTED (or EXIT_SHRINK/EXIT_GROW for injected elastic
    reshape requests) so the process exit code is distinct."""

    def __init__(self, message="", code=EXIT_PREEMPTED):
        super().__init__(code)
        self.message = message


# ---------------------------------------------------------------------------
# enable / install
# ---------------------------------------------------------------------------

def enabled():
    """True when the resilience layer is armed (hot paths read the module
    global `_enabled` directly — this accessor is the public spelling)."""
    return _enabled


def enable():
    """Arm the trainer hooks (periodic checkpoint, fault injection, resume)
    WITHOUT touching signal handlers — install() adds those."""
    global _enabled, _injector
    with _lock:
        _injector = FaultInjector.from_config()
        _enabled = True


def disable():
    global _enabled
    _enabled = False


def install(signals=(_signal.SIGTERM, _signal.SIGINT)):
    """Arm everything: enable() plus a preemption handler on `signals`
    that only sets a flag (async-signal-safe); the in-flight step finishes,
    a final checkpoint is written at the step boundary, and the process
    exits EXIT_PREEMPTED. Also publishes the supervised-relaunch count
    (MXNET_TPU_RESTART_COUNT) into the restarts_total counter and the
    diagnostics ring. Idempotent."""
    global _installed
    enable()
    with _lock:
        if not _installed:
            for sig in signals:
                try:
                    _prev_handlers[sig] = _signal.signal(sig, _on_signal)
                except (ValueError, OSError):
                    pass           # non-main thread / restricted env
            _installed = True
    n = restart_count()
    if n:
        _M_RESTARTS.inc(n)
        _diagnostics.record_event("restart", count=n)
    return _installed


def uninstall():
    """Undo install() (tests): restore previous signal handlers, disarm
    the hooks, drop the preemption flag and per-trainer managers."""
    global _injector, _resume_info, _pending_reshard
    with _lock:
        if _installed:
            _restore_handlers()
        _injector = None
        _resume_info = None
        _pending_reshard = None
        clear_preempted()
    disable()


def _on_signal(signum, frame):
    # First signal: set a flag, nothing else — saving from the signal
    # frame mid-dispatch could serialize half-updated device state; the
    # trainer/fit loop checks the flag at the next step boundary.
    # Second signal: ESCALATE — restore the previous handlers and
    # re-deliver, so a phase with no step boundary in sight (data prep,
    # a minutes-long first compile, a plain user loop with no resilience
    # hook) stays terminable and Ctrl-C twice still kills the process.
    if _preempt["flag"]:
        print("mx.resilience: second signal — restoring default handlers "
              "and terminating without a final checkpoint", file=sys.stderr)
        _restore_handlers()
        os.kill(os.getpid(), signum)
        return
    _preempt["flag"] = True
    _preempt["signum"] = signum
    print(f"mx.resilience: signal {signum} received — finishing the "
          "in-flight step, then checkpointing and exiting "
          f"{EXIT_PREEMPTED} (send again to terminate immediately)",
          file=sys.stderr)


def _restore_handlers():
    global _installed
    for sig, h in list(_prev_handlers.items()):
        try:
            _signal.signal(sig, h if h is not None else _signal.SIG_DFL)
        except (ValueError, OSError):
            pass
    _prev_handlers.clear()
    _installed = False


def preempted():
    """True once a preemption signal arrived (sticky until
    clear_preempted(); the boundary save does not clear it — training
    loops break on it)."""
    return _preempt["flag"]


def clear_preempted():
    _preempt["flag"] = False
    _preempt["signum"] = None
    _preempt.pop("resize", None)


def restart_count():
    """How many supervised relaunches this process has been through
    (exported by tools/launch.py --max-restarts as
    MXNET_TPU_RESTART_COUNT; 0 on the first launch)."""
    try:
        return int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    except ValueError:
        return 0


def last_resume():
    """{"path", "step", "fallbacks"} of the most recent successful restore
    in this process (None before any). Surfaced as the post-mortem
    "resume" section by mx.diagnostics."""
    return dict(_resume_info) if _resume_info else None


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Exponential backoff + full jitter + retryable-exception
    classification.

    `max_attempts` counts TOTAL tries (1 = no retry). A non-retryable
    exception propagates immediately; a retryable one sleeps
    `backoff_s * 2^k` (capped at `max_backoff_s`, jittered by ±`jitter`
    fraction) and tries again. `call(fn, ..., abort=...)` stops early —
    re-raising the last failure — when the abort callable turns true
    (e.g. a prefetcher closing under the worker)."""

    #: transient by default: filesystem/network hiccups and timeouts.
    #: Framework code passes explicit lists where it knows better.
    DEFAULT_RETRYABLE = (OSError, ConnectionError, TimeoutError)

    def __init__(self, max_attempts=None, backoff_s=None, max_backoff_s=None,
                 jitter=0.25, retryable=None, sleep=time.sleep, rng=None):
        self.max_attempts = int(max_attempts if max_attempts is not None
                                else _config.get("retry_max_attempts"))
        self.backoff_s = float(backoff_s if backoff_s is not None
                               else _config.get("retry_backoff_s"))
        self.max_backoff_s = float(max_backoff_s if max_backoff_s is not None
                                   else _config.get("retry_max_backoff_s"))
        self.jitter = float(jitter)
        self.retryable = tuple(retryable) if retryable is not None \
            else self.DEFAULT_RETRYABLE
        self._sleep = sleep
        self._rng = rng or _pyrandom.Random()

    def is_retryable(self, exc):
        return isinstance(exc, self.retryable)

    def delay(self, attempt):
        """Backoff before try `attempt+2` (attempt is the 0-based index of
        the try that just failed)."""
        base = min(self.backoff_s * (2.0 ** attempt), self.max_backoff_s)
        if self.jitter:
            base *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, base)

    def call(self, fn, *args, site="generic", abort=None, on_retry=None,
             **kwargs):
        """Run fn(*args, **kwargs) under this policy. `on_retry(exc,
        attempt, delay)` observes each retry; `abort()` true stops the
        loop early, re-raising the last exception."""
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self.is_retryable(e) or attempt + 1 >= self.max_attempts:
                    raise
                if abort is not None and abort():
                    raise
                delay = self.delay(attempt)
                if _telemetry._enabled:
                    _M_RETRIES.labels(site=site).inc()
                if on_retry is not None:
                    on_retry(e, attempt, delay)
                else:
                    print(f"mx.resilience: retrying {site} after "
                          f"{type(e).__name__}: {e} (attempt "
                          f"{attempt + 2}/{self.max_attempts}, "
                          f"backoff {delay:.2f}s)", file=sys.stderr)
                self._sleep(delay)
                attempt += 1


def retry_call(fn, *args, **kwargs):
    """Module-level convenience: RetryPolicy() from the config knobs."""
    return RetryPolicy().call(fn, *args, **kwargs)


# ---------------------------------------------------------------------------
# atomic verified checkpoints
# ---------------------------------------------------------------------------

_MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"
_TMP_MARK = ".tmp-"


def _file_crc(path, _bufsize=1 << 20):
    """Streaming CRC32 of one file (cheap enough to run over multi-GB
    checkpoints; the point is torn-write detection, not cryptography)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_bufsize)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _walk_files(root):
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            yield os.path.relpath(full, root), full


def _jax_process_count():
    """jax.process_count() without cold-initializing a backend: a process
    that never imported jax cannot be part of a multi-host world."""
    jax = sys.modules.get("jax")
    if jax is None:
        return 1
    try:
        return int(jax.process_count())
    except Exception:
        return 1


def write_checkpoint(directory, writer, step=0, fingerprint=None,
                     layouts=None):
    """Atomic verified checkpoint write.

    `writer(tmpdir)` produces the payload (orbax state, .params files,
    anything); then a manifest.json with per-file size+CRC32, the step id
    and the caller's fingerprint is written, everything is fsynced, and
    the temp directory is atomically renamed to `directory` (an existing
    checkpoint there is replaced — see _recover_displaced for the
    crash-between-renames window). A crash leaves either the previous
    checkpoint, a recoverable `*.tmp-old` displacement, or an ignorable
    `*.tmp-<pid>` directory — never a half-written checkpoint that
    restore would trust.

    Multi-host (jax.process_count() > 1): the temp-dir rename dance is a
    per-process filesystem operation and cannot wrap a COLLECTIVE orbax
    save, so the writer runs against the final directory directly (orbax
    brings its own multi-host commit semantics) and only process 0 writes
    the manifest afterwards — shared-filesystem assumption, like the
    orbax layout itself."""
    directory = os.path.abspath(str(directory))
    parent = os.path.dirname(directory) or "."
    os.makedirs(parent, exist_ok=True)
    if _jax_process_count() > 1:
        writer(directory)
        if _process_index() == 0:
            _write_manifest(directory, step, fingerprint, layouts)
        fault_point("ckpt", step=step, path=directory)
        return directory
    tmp = directory + _TMP_MARK + str(os.getpid())
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        writer(tmp)
        _write_manifest(tmp, step, fingerprint, layouts)
        if os.path.exists(directory):
            # replace-in-place: move the old checkpoint aside first (rename
            # over a non-empty directory is not atomic/portable), remove it
            # only after the new one is in place. A crash between the two
            # renames leaves the good copy at <dir>.tmp-old, which
            # _recover_displaced renames back on the next restore/GC.
            old = directory + _TMP_MARK + "old"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(directory, old)
            os.rename(tmp, directory)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _dir_fsync(parent)
    fault_point("ckpt", step=step, path=directory)
    return directory


def _write_manifest(directory, step, fingerprint, layouts=None):
    manifest = {
        "schema": 2,
        "step": int(step),
        "ts": time.time(),
        "fingerprint": fingerprint or {},
        "files": {},
    }
    if layouts:
        # per-array shard layouts (parallel/reshard.state_layouts): lets a
        # restore on a DIFFERENT topology plan the redistribution from
        # metadata alone, before touching any payload
        manifest["shardings"] = list(layouts)
    for rel, full in _walk_files(directory):
        if rel == _MANIFEST:
            continue
        manifest["files"][rel] = {"size": os.path.getsize(full),
                                  "crc32": _file_crc(full)}
    mpath = os.path.join(directory, _MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())


def _recover_displaced(base_dir):
    """Undo a crash caught between write_checkpoint's two renames: a
    `step_X.tmp-old` directory whose `step_X` is missing IS the last good
    checkpoint — rename it back before anyone lists or GCs."""
    try:
        entries = os.listdir(str(base_dir))
    except (FileNotFoundError, NotADirectoryError):
        return
    suffix = _TMP_MARK + "old"
    for name in entries:
        if not (name.startswith(_STEP_PREFIX) and name.endswith(suffix)):
            continue
        final = os.path.join(str(base_dir), name[:-len(suffix)])
        if not os.path.exists(final):
            try:
                os.rename(os.path.join(str(base_dir), name), final)
                print(f"mx.resilience: recovered displaced checkpoint "
                      f"{final} (crash during a same-step rewrite)",
                      file=sys.stderr)
            except OSError:
                pass


def _dir_fsync(path):
    """fsync a directory so the rename itself is durable (best-effort:
    not all filesystems/platforms allow O_RDONLY dir fds + fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def verify_checkpoint(directory):
    """Verify a managed checkpoint: manifest present, every entry present
    with matching size and CRC32. Returns the manifest dict; raises
    CheckpointCorruptError naming the first bad file."""
    directory = str(directory)
    mpath = os.path.join(directory, _MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"{directory}: no {_MANIFEST} — torn write or not a managed "
            "checkpoint") from None
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{directory}: unreadable {_MANIFEST}: {e}") from None
    for rel, info in manifest.get("files", {}).items():
        full = os.path.join(directory, rel)
        if not os.path.exists(full):
            raise CheckpointCorruptError(f"{directory}: missing file {rel}")
        size = os.path.getsize(full)
        if size != info.get("size"):
            raise CheckpointCorruptError(
                f"{directory}: {rel} is {size} bytes, manifest says "
                f"{info.get('size')}")
        crc = _file_crc(full)
        if crc != info.get("crc32"):
            raise CheckpointCorruptError(
                f"{directory}: {rel} checksum {crc:#010x} != manifest "
                f"{info.get('crc32', 0):#010x} (corrupt)")
    return manifest


#: fingerprint keys a planned redistribution can bridge — anything else
#: differing (e.g. the trainer class) is structural, not topological.
#: "zero" (mx.zero optimizer-state sharding on/off) is a pure layout
#: change: a zero'd checkpoint restores onto an unsharded trainer and
#: vice versa, bit-exactly, via the same planned-reshard path
RESHARDABLE_KEYS = frozenset({"mesh_shape", "param_mode", "zero"})


def check_fingerprint(manifest, expected, directory=""):
    """Reject a checkpoint written for a different mesh/config. Compares
    only the keys `expected` carries, so new fingerprint fields stay
    backward-compatible. The raised MeshMismatchError names BOTH
    fingerprints and the reshard='auto' remediation; callers that may
    redistribute go through reshard_gate() instead."""
    got = manifest.get("fingerprint") or {}
    bad = {k: (got.get(k), v) for k, v in (expected or {}).items()
           if k in got and got[k] != v}
    if bad:
        detail = ", ".join(f"{k}: checkpoint={g!r} current={c!r}"
                           for k, (g, c) in sorted(bad.items()))
        raise MeshMismatchError(
            f"checkpoint {directory or '<dir>'} was written for a different "
            f"topology ({detail}; checkpoint fingerprint {got!r}, current "
            f"{expected!r}). Pass reshard='auto' to load_states / set the "
            "reshard knob (MXNET_TPU_RESHARD=auto) to redistribute it onto "
            "the current mesh, or restore on the original topology.",
            mismatch=bad)


def reshard_gate(manifest, trainer, directory="", reshard=None):
    """check_fingerprint with redistribution awareness: returns False when
    the checkpoint matches the trainer's topology, True when it differs
    ONLY in mesh/param-mode and the reshard policy ('auto'/'host', from
    the argument or the `reshard` knob) allows redistribution. Raises
    MeshMismatchError when resharding is explicitly off, and for
    structural mismatches (different trainer class) regardless of
    policy — no redistribution can bridge those."""
    mode = reshard if reshard not in (None, "") else _config.get("reshard")
    if mode not in ("auto", "off", "host"):
        # an unvalidated per-call override must not fail open: a typo like
        # 'none' silently behaving as 'auto' would reshard exactly where
        # the caller asked for the strict check
        raise ValueError(
            f"reshard={mode!r}: expected 'auto', 'off', or 'host'")
    try:
        check_fingerprint(manifest, trainer_fingerprint(trainer), directory)
    except MeshMismatchError as e:
        if mode == "off" or not e.mismatch \
                or set(e.mismatch) - RESHARDABLE_KEYS:
            raise
        return True
    return False


def list_checkpoints(base_dir):
    """Step-numbered managed checkpoints under base_dir, oldest first:
    [(step, path)]. `*.tmp-*` leftovers from killed saves are excluded."""
    out = []
    try:
        entries = os.listdir(str(base_dir))
    except (FileNotFoundError, NotADirectoryError):
        return out
    for name in entries:
        if not name.startswith(_STEP_PREFIX) or _TMP_MARK in name:
            continue
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        out.append((step, os.path.join(str(base_dir), name)))
    return sorted(out)


class CheckpointManager:
    """Keep-last-N atomic verified checkpoints of one trainer under
    `base_dir/step_<n>`.

    `trainer` is anything exposing save_states/load_states/num_update
    (ShardedTrainer, the pipeline trainers). Saves go through
    write_checkpoint (manifest + atomic rename) under the checkpoint-I/O
    RetryPolicy; restore_latest walks newest→oldest, verifying checksums
    and the mesh fingerprint, falling back past corrupt checkpoints and
    GCing beyond `keep` after each save."""

    def __init__(self, trainer, base_dir, keep=None, policy=None):
        self.trainer = trainer
        self.base_dir = os.path.abspath(str(base_dir))
        self.keep = int(keep if keep is not None
                        else _config.get("checkpoint_keep"))
        self.policy = policy or RetryPolicy()
        self._last_saved_step = None

    # ------------------------------------------------------------- save
    def _step_dir(self, step):
        return os.path.join(self.base_dir, f"{_STEP_PREFIX}{step:010d}")

    def save(self, force=False):
        """Checkpoint the trainer's current step. Skips (returns None) if
        that step is already saved, unless `force`. The write itself is
        atomic+verified: while resilience is enabled, the trainer's
        save_states routes through write_checkpoint (see
        parallel/trainer._ckpt_save)."""
        step = int(self.trainer.num_update)
        if not force and self._last_saved_step == step:
            return None
        t0 = time.perf_counter()
        path = self._step_dir(step)
        if _guard._enabled:
            # liveness: the supervisor's staleness clock must see the
            # save START (a long write is progress, not a hang)
            _guard.heartbeat(step, phase="checkpoint.save", force=True)
        # a multi-GB (or resharding) checkpoint write is a legitimate
        # long non-step region: suspend the hang watchdog and the
        # mx.guard collective deadline for its duration so neither can
        # falsely fire mid-save (a REAL hang inside still gets named —
        # the suspend context doubles as a diagnostics scope)
        with _diagnostics.suspend_watchdog("checkpoint.save", step):
            self.policy.call(self.trainer.save_states, path,
                             site="checkpoint-io")
        if _guard._enabled:
            _guard.heartbeat(step, phase="checkpoint.save", force=True)
        self._last_saved_step = step
        dt = time.perf_counter() - t0
        if _telemetry._enabled:
            _M_SAVE_SECONDS.observe(dt)
            _telemetry.event("checkpoint", step=step, path=path,
                             dur_s=round(dt, 6))
        if _trace._enabled:
            # checkpoint saves serialize with the step loop on this rank:
            # a gang whose straggler's timeline shows checkpoint.save where
            # the peers show step spans is checkpoint-bound, not slow
            _trace.record_span("checkpoint.save", t0, t0 + dt, step=step,
                               cat="checkpoint", always=True)
        if _goodput._enabled:
            _goodput.note("checkpoint_save", t0, t0 + dt, step=step)
        _diagnostics.record_event("checkpoint", step=step, path=path,
                                  dur_s=round(dt, 6))
        self._gc()
        return path

    def _gc(self):
        """Retention on process 0: newest `keep` complete checkpoints
        survive; older ones and stale tmp leftovers (killed mid-save,
        older than 5 minutes) go. Displaced `*.tmp-old` checkpoints are
        recovered first so the cleanup can never eat the last good copy."""
        if self.keep <= 0 or not _owns_gc():
            return
        _recover_displaced(self.base_dir)
        for _step, path in list_checkpoints(self.base_dir)[:-self.keep]:
            shutil.rmtree(path, ignore_errors=True)
        try:
            for name in os.listdir(self.base_dir):
                full = os.path.join(self.base_dir, name)
                if _TMP_MARK in name and \
                        time.time() - os.path.getmtime(full) > 300:
                    shutil.rmtree(full, ignore_errors=True)
        except OSError:
            pass

    # ---------------------------------------------------------- restore
    def restore_latest(self, max_step=None):
        """Restore the newest checkpoint that verifies, falling back past
        torn/corrupt ones (each rejection counts
        checkpoint_verify_failures_total). Returns the restored step, or
        None when no usable checkpoint exists. `max_step` bounds the
        search: checkpoints above it are skipped without being counted as
        corrupt (mx.guard's SDC rollback passes the last digest-verified
        step — a CRC-clean file saved from already-corrupt params must
        not be reloaded). A mesh-mismatch raises MeshMismatchError —
        that is a configuration error, not corruption, and older
        checkpoints would mismatch identically."""
        _recover_displaced(self.base_dir)
        ckpts = list_checkpoints(self.base_dir)
        fallbacks = 0
        for step, path in reversed(ckpts):
            if max_step is not None and step > max_step:
                continue
            try:
                self.restore(path)
            except CheckpointCorruptError as e:
                fallbacks += 1
                if _telemetry._enabled:
                    _M_VERIFY_FAILURES.inc()
                print(f"mx.resilience: rejecting checkpoint: {e} — "
                      "falling back to the previous one", file=sys.stderr)
                continue
            _note_resume(path, step, fallbacks)
            return step
        return None

    def restore(self, path):
        """Verify + load one specific checkpoint directory. The checksum
        and fingerprint verification happen INSIDE load_states (the
        trainer's _ckpt_restore verifies whenever resilience is enabled
        and a manifest exists) — running them here too would CRC every
        payload file twice on exactly the relaunch path where recovery
        speed matters; this only insists a manifest is present so an
        unmanaged directory can't slip through unverified."""
        global _pending_reshard
        t0 = time.perf_counter()
        # drop any transition staged by an earlier, unrelated load_states
        # call: only a reshard that happens DURING this restore may attach
        # to the resume record _note_resume writes afterwards
        _pending_reshard = None
        if not os.path.exists(os.path.join(str(path), _MANIFEST)):
            raise CheckpointCorruptError(
                f"{path}: no {_MANIFEST} — torn write or not a managed "
                "checkpoint")
        if not _enabled:
            # load_states only self-verifies while resilience is enabled;
            # a manager used standalone still gets the full check here
            # (reshard_gate: a pure topology change passes through while
            # the reshard knob allows redistribution)
            manifest = verify_checkpoint(path)
            reshard_gate(manifest, self.trainer, str(path))
        # restores (possibly resharding onto a new topology) are long
        # non-step regions too: same watchdog/deadline suspension as save
        with _diagnostics.suspend_watchdog("checkpoint.restore"):
            self.policy.call(self.trainer.load_states, path,
                             site="checkpoint-io")
        if _guard._enabled:
            _guard.heartbeat(int(self.trainer.num_update),
                             phase="checkpoint.restore", force=True)
        self._last_saved_step = int(self.trainer.num_update)
        if _telemetry._enabled:
            _M_RESTORE_SECONDS.observe(time.perf_counter() - t0)
        if _goodput._enabled:
            _goodput.note("checkpoint_restore", t0, time.perf_counter(),
                          step=int(self.trainer.num_update))
        return path

    def last_saved_path(self):
        """Path of this manager's most recent save (None before any)."""
        if self._last_saved_step is None:
            return None
        return self._step_dir(self._last_saved_step)


def trainer_fingerprint(trainer):
    """The topology identity a trainer checkpoint is only valid on:
    trainer class, mesh axis sizes, param mode. Written into the manifest
    at save; compared (key-wise) at verified restore."""
    fp = {"trainer": type(trainer).__name__}
    mesh = getattr(trainer, "mesh", None)
    if mesh is not None:
        try:
            fp["mesh_shape"] = {str(k): int(v)
                                for k, v in dict(mesh.shape).items()}
        except Exception:
            pass
    mode = getattr(trainer, "param_mode", None)
    if mode is not None:
        fp["param_mode"] = mode
    if hasattr(trainer, "_zero"):
        # mx.zero layout identity: restores across the zero'd/unsharded
        # boundary are planned redistributions, not mismatches
        fp["zero"] = bool(trainer._zero)
    return fp


def _process_index():
    """Process index without cold-initializing a backend: env first
    (tools/launch.py exports JAX_PROCESS_ID), then jax.process_index()
    if jax is already imported — the same detection order and jax
    fallback as _jax_process_count, so the multi-host checkpoint path
    can never see count>1 while every host thinks it is index 0."""
    for var in ("JAX_PROCESS_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


def _owns_gc():
    """True when this process may delete checkpoints: in a multi-host
    jax world only process 0 (the directory is shared), but a process
    that is its own single-process world owns its checkpoint_dir
    outright — per-rank directories (env rank set, no jax.distributed)
    must still get retention."""
    return _jax_process_count() == 1 or _process_index() == 0


def _note_resume(path, step, fallbacks=0):
    global _resume_info
    _resume_info = {"path": path, "step": int(step),
                    "fallbacks": int(fallbacks)}
    # topology transition, when this resume redistributed across meshes
    # (_pending_reshard staged by reshard.note_reshard during the restore
    # that just finished): the post-mortem resume section then names the
    # reshape. Consumed here so a later same-topology resume can't
    # inherit a stale transition.
    global _pending_reshard
    if _pending_reshard is not None:
        _resume_info["reshard"] = _pending_reshard
        _pending_reshard = None
    print(f"mx.resilience: resumed from {path} (step {step}"
          + (f", {fallbacks} corrupt checkpoint(s) skipped" if fallbacks
             else "") + ")", file=sys.stderr)
    if _telemetry._enabled:
        _telemetry.event("resume", path=path, step=int(step),
                         fallbacks=fallbacks)
    _diagnostics.record_event("resume", path=path, step=int(step),
                              fallbacks=fallbacks)
    if _goodput._enabled:
        # marker for the offline report: replayed-step count must equal
        # the high-water mark minus this restored step
        _goodput.note_resume(int(step))


# ---------------------------------------------------------------------------
# trainer hooks (ShardedTrainer / pipeline trainers call these; both are
# gated on the module bool so the disabled path is one check)
# ---------------------------------------------------------------------------

def manager_for(trainer, base_dir=None):
    """Get-or-create the CheckpointManager for a trainer (None when no
    checkpoint directory is configured). Cached ON the trainer object so
    the manager's lifetime is exactly the trainer's — a module-level map
    would pin every trainer (params, optimizer state and all) for the
    life of the process."""
    base_dir = base_dir or _config.get("checkpoint_dir")
    if not base_dir:
        return None
    mgr = getattr(trainer, "_resilience_mgr", None)
    if mgr is None or os.path.abspath(str(base_dir)) != mgr.base_dir:
        mgr = CheckpointManager(trainer, base_dir)
        trainer._resilience_mgr = mgr
    return mgr


def on_trainer_init(trainer):
    """Called at ShardedTrainer construction while enabled: auto-resume
    per the `resume` knob ("auto" = newest verified checkpoint under
    checkpoint_dir; an explicit path = that checkpoint, verified)."""
    resume = _config.get("resume")
    if not resume:
        return None
    if not getattr(trainer, "_ready", True):
        print("mx.resilience: trainer has deferred-shape parameters — "
              "auto-resume skipped (run one step, then load_states "
              "explicitly)", file=sys.stderr)
        return None
    if resume == "auto":
        mgr = manager_for(trainer)
        if mgr is None:
            return None
        return mgr.restore_latest()
    mgr = CheckpointManager(trainer, os.path.dirname(
        os.path.abspath(resume)) or ".")
    mgr.restore(resume)
    _note_resume(resume, int(trainer.num_update))
    return int(trainer.num_update)


def on_step(trainer):
    """The per-step resilience hook (called only while enabled): periodic
    checkpoint FIRST (so a same-step fault resumes past itself), then
    fault injection, then the preemption flag — finishing the in-flight
    step, writing a final checkpoint, and exiting EXIT_PREEMPTED."""
    step = int(trainer.num_update)
    mgr = manager_for(trainer)
    every = _config.get("checkpoint_every_n_steps")
    if mgr is not None and every > 0 and step % every == 0:
        mgr.save()
    if _injector is not None:
        _injector.fire("step", step=step, trainer=trainer)
    if _preempt["flag"]:
        _finalize_preemption(mgr, step)


def request_shrink(reason=None):
    """Ask this rank out of the gang at the NEXT step boundary:
    piggybacks on the preemption machinery — on_step's flag check saves
    a final checkpoint and raises PreemptedExit(EXIT_SHRINK), so a
    tools/launch.py --elastic supervisor relaunches the gang one worker
    smaller without this rank. How mx.guard quarantines a repeat-SDC
    rank (hardware corrupting data faster than rollback launders it)."""
    print(f"mx.resilience: shrink requested"
          + (f" ({reason})" if reason else "")
          + " — exiting EXIT_SHRINK at the next step boundary",
          file=sys.stderr)
    _preempt["flag"] = True
    _preempt["resize"] = "shrink"


def note_preemption(step, path=None, signum=None, kind=None):
    """Record one graceful preemption in telemetry + diagnostics (shared
    by the trainer and estimator preemption paths, so preemptions_total
    means the same thing whichever loop handled the signal). `kind` marks
    injected elastic reshape requests ("shrink"/"grow") apart from real
    preemptions."""
    signum = signum if signum is not None else _preempt["signum"]
    if _telemetry._enabled:
        _M_PREEMPTIONS.inc()
        _telemetry.event("preempt", step=step, signum=signum, path=path,
                         request=kind or "preempt")
    _diagnostics.record_event("preempt", step=step, signum=signum,
                              path=path, request=kind or "preempt")


def _finalize_preemption(mgr, step):
    signum = _preempt["signum"]
    resize = _preempt.get("resize")
    path = None
    save_failed = False
    if mgr is not None:
        try:
            # save() dedupes a step the periodic hook just wrote — that
            # existing checkpoint is still THE final state, so report it
            path = mgr.save() or mgr.last_saved_path()
        except Exception as e:         # noqa: BLE001 — still exit, loudly
            save_failed = True
            print(f"mx.resilience: final preemption checkpoint failed: {e}",
                  file=sys.stderr)
    note_preemption(step, path=path, signum=signum, kind=resize)
    if save_failed:
        # EXIT_PREEMPTED means "state saved, safe to resume the last
        # interval" — a failed final save must NOT claim it. Exit with
        # the conventional fatal-signal code so supervisors see the loss.
        code = 128 + int(signum or _signal.SIGTERM)
        print(f"mx.resilience: preempted (signal {signum}) but the final "
              f"checkpoint FAILED — exiting {code}, resume will use the "
              "last periodic checkpoint", file=sys.stderr)
        raise SystemExit(code)
    code = {"shrink": EXIT_SHRINK, "grow": EXIT_GROW}.get(resize,
                                                          EXIT_PREEMPTED)
    what = f"{resize} requested" if resize else f"preempted (signal {signum})"
    msg = (f"mx.resilience: {what} — "
           + (f"checkpoint saved at step {step} ({path}); " if path
              else "no checkpoint_dir configured; ")
           + f"exiting {code}"
           + (" (an elastic supervisor reshapes the gang)" if resize else ""))
    print(msg, file=sys.stderr)
    raise PreemptedExit(msg, code=code)


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

class FaultInjector:
    """Deterministic fault injection driven by the `fault_inject` knob.

    Spec grammar (comma-separated list):
      sigterm@step:5        — raise SIGTERM in-process after step 5 completes
      kill@step:3           — SIGKILL the process after step 3 (rank death)
      corrupt_ckpt@step:4   — flip bytes in the checkpoint written at step 4
                              (AFTER its manifest: restore must detect it)
      stall_input:250       — one 250 ms stall inside the input pipeline
      exc@step:2            — raise RuntimeError after step 2 (crash path)
      oom@step:3            — raise a synthetic RESOURCE_EXHAUSTED at the
                              DISPATCH of step 3 (before any transfer or
                              donation, like a pre-flight rejection), so
                              every rung of the mx.memsafe oom_recover
                              degradation ladder is drivable in tests;
                              repeat the spec to OOM the retry too and
                              walk further rungs
      shrink@step:3         — after step 3: save a final checkpoint and exit
                              EXIT_SHRINK (84) — an elastic supervisor
                              relaunches the gang SMALLER by every rank
                              that fired (append @rank:N to lose exactly
                              one worker; untargeted, the whole gang
                              shrinks to the --min-workers floor); the
                              resumed workers reshard the checkpoint onto
                              the surviving topology
      grow@step:3           — same, exit EXIT_GROW (85): relaunch one
                              worker LARGER (capacity returned), capped at
                              the original -n
      hang@step:3           — the step-3 boundary BLOCKS and never
                              returns: a stuck collective / wedged host.
                              The heartbeat goes stale, the tools/
                              launch.py --heartbeat-timeout poll kills
                              the stuck-but-alive process (slot loss →
                              elastic relaunch), and any peer stuck
                              waiting trips its mx.guard collective
                              deadline
      corrupt_grad@step:4   — deterministic bit-flip in ONE REPLICA of
                              the first gradient/parameter leaf as the
                              step-4 update lands — the silent data
                              corruption the mx.guard digest vote must
                              catch, attribute by majority, and roll
                              back past
      stall_heartbeat:500   — suppress heartbeat FILE writes for 500 ms
                              (consumed by mx.guard at its next beat):
                              the process stays healthy, only its
                              liveness signal goes dark — the
                              supervisor-side staleness drill
      slow_client:200       — mx.serve: the request STREAM consumer
                              stalls 200 ms per token (consumed by
                              Request.stream at its first read); the
                              scheduler's throughput must not care
      burst:8@step:3        — mx.serve: at scheduler step 3 the server
                              fires its on_burst hook with 8 — a
                              deterministic load spike driving the
                              shed / backpressure paths
      cancel@req:2          — mx.serve: cancel request id 2 at the next
                              scheduler step (append @step:N to pick
                              the step) — the mid-generation
                              cancellation drill; the slot is evicted
                              between decode steps
      kill_replica@step:3   — mx.fleet: SIGKILL this serving replica at
                              scheduler step 3, mid-generation — the
                              router must fail its in-flight requests
                              over to survivors (bit-identical replay
                              past the streamed high-water) and the
                              supervisor must relaunch the worker
      wedge_replica@step:3  — mx.fleet: park the serving scheduler
                              forever at step 3 WITHOUT dying — health
                              checks keep answering while tokens stop;
                              the router's per-read stall bound
                              (fleet_stall_timeout_ms) must fail over
      slow_replica:200      — mx.fleet: this replica's endpoint delays
                              every streamed token 200 ms (consumed by
                              the ReplicaEndpoint at its first submit)
                              — published TTFT degrades and placement
                              must shift load to faster replicas
    Any spec may append @rank:N to fire on that rank only. Specs fire at
    most once, and only on the FIRST launch (MXNET_TPU_RESTART_COUNT=0)
    unless @every_restart is appended — a relaunched gang must not re-kill
    itself at the same step forever."""

    def __init__(self, specs):
        self._specs = list(specs)

    @classmethod
    def from_config(cls):
        raw = _config.get("fault_inject")
        if not raw:
            return None
        return cls.parse(raw)

    @classmethod
    def parse(cls, raw):
        specs = []
        for part in str(raw).split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split("@")
            head = fields[0]
            kind, _, arg = head.partition(":")
            spec = {"kind": kind, "arg": arg, "step": None, "rank": None,
                    "req": None, "every_restart": False, "fired": False}
            for field in fields[1:]:
                k, _, v = field.partition(":")
                if k == "step":
                    spec["step"] = int(v)
                elif k == "rank":
                    spec["rank"] = int(v)
                elif k == "req":
                    spec["req"] = int(v)
                elif k == "every_restart":
                    spec["every_restart"] = True
                else:
                    raise ValueError(
                        f"fault_inject: unknown qualifier {field!r} in "
                        f"{part!r}")
            if spec["kind"] not in ("sigterm", "kill", "corrupt_ckpt",
                                    "stall_input", "exc", "shrink", "grow",
                                    "oom", "hang", "corrupt_grad",
                                    "stall_heartbeat", "slow_client",
                                    "burst", "cancel", "kill_replica",
                                    "wedge_replica", "slow_replica"):
                raise ValueError(
                    f"fault_inject: unknown fault {spec['kind']!r} in "
                    f"{part!r} (know: sigterm, kill, corrupt_ckpt, "
                    "stall_input, exc, shrink, grow, oom, hang, "
                    "corrupt_grad, stall_heartbeat, slow_client, burst, "
                    "cancel, kill_replica, wedge_replica, slow_replica)")
            specs.append(spec)
        return cls(specs)

    def fire(self, point, step=None, path=None, trainer=None):
        """Run every armed spec matching this fault point. `point` is
        "step" (trainer step boundary), "dispatch" (about to dispatch a
        step; nothing transferred or donated yet), "ckpt" (checkpoint
        just written), or "input" (input pipeline worker). `trainer` is
        handed through at the step boundary so corrupt_grad can reach
        the live parameter replicas."""
        rank = _process_index()
        for spec in self._specs:
            if spec["fired"]:
                continue
            if spec["rank"] is not None and spec["rank"] != rank:
                continue
            if not spec["every_restart"] and restart_count() > 0:
                continue
            kind = spec["kind"]
            if point == "step" and kind in ("sigterm", "kill", "exc",
                                            "hang"):
                if spec["step"] is not None and step != spec["step"]:
                    continue
                spec["fired"] = True
                self._fire_process_fault(kind, step)
            elif point == "step" and kind == "corrupt_grad":
                if spec["step"] is not None and step != spec["step"]:
                    continue
                spec["fired"] = True
                self.corrupt_gradient(trainer, step)
            elif point == "step" and kind in ("shrink", "grow"):
                if spec["step"] is not None and step != spec["step"]:
                    continue
                spec["fired"] = True
                # elastic reshape request: piggyback on the preemption
                # machinery — on_step's flag check (which runs AFTER this
                # fire, in the same step boundary) saves the final
                # checkpoint and exits EXIT_SHRINK/EXIT_GROW
                print(f"mx.resilience: fault injection: {kind} at step "
                      f"{step} (rank {_process_index()})", file=sys.stderr)
                _preempt["flag"] = True
                _preempt["resize"] = kind
            elif point == "dispatch" and kind == "oom":
                if spec["step"] is not None and step != spec["step"]:
                    continue
                spec["fired"] = True
                print(f"mx.resilience: fault injection: synthetic "
                      f"RESOURCE_EXHAUSTED at dispatch of step {step} "
                      f"(rank {rank})", file=sys.stderr)
                from . import memsafe as _memsafe
                raise _memsafe.SimulatedResourceExhausted(step=step)
            elif point == "ckpt" and kind == "corrupt_ckpt":
                if spec["step"] is not None and step != spec["step"]:
                    continue
                spec["fired"] = True
                self.corrupt_checkpoint(path)
            elif point == "input" and kind == "stall_input":
                spec["fired"] = True
                ms = float(spec["arg"] or 100)
                print(f"mx.resilience: fault injection: stalling input "
                      f"{ms:.0f} ms", file=sys.stderr)
                time.sleep(ms / 1000.0)

    def _fire_process_fault(self, kind, step):
        print(f"mx.resilience: fault injection: {kind} at step {step} "
              f"(rank {_process_index()})", file=sys.stderr)
        sys.stderr.flush()
        if kind == "sigterm":
            os.kill(os.getpid(), _signal.SIGTERM)
        elif kind == "kill":
            os.kill(os.getpid(), _signal.SIGKILL)   # no cleanup: rank death
        elif kind == "exc":
            raise RuntimeError(
                f"mx.resilience fault injection: crash at step {step}")
        elif kind == "hang":
            # stuck collective / wedged host: the step boundary never
            # returns. SIGTERM can't break the loop (the resilience
            # handler is flag-only by design) — exactly the stuck-but-
            # alive process the heartbeat-staleness kill exists for.
            while True:
                time.sleep(3600)

    def take(self, kind, step=None, ready=None):
        """Pop one armed spec of `kind` for a caller that implements the
        fault itself (mx.serve's scheduler: burst, cancel). Honors @rank
        and the one-shot / first-launch-only disarm rules; a spec with
        @step:N fires only when `step` matches, a step-less spec fires
        at the first opportunity. `ready(spec)` False leaves the spec
        ARMED instead of consuming it — how a step-less cancel@req:N
        waits for request N to exist rather than burning itself on an
        idle scheduler tick. Returns {"arg", "req"} or None."""
        rank = _process_index()
        for spec in self._specs:
            if spec["fired"] or spec["kind"] != kind:
                continue
            if spec["rank"] is not None and spec["rank"] != rank:
                continue
            if not spec["every_restart"] and restart_count() > 0:
                continue
            if spec["step"] is not None and step != spec["step"]:
                continue
            if ready is not None and not ready(spec):
                continue
            spec["fired"] = True
            return {"arg": spec["arg"] or "", "req": spec["req"]}
        return None

    def consume(self, kind):
        """Pop one armed spec of `kind` (honoring @rank targeting and
        the one-shot / first-launch-only disarm rules) and return its
        arg string, or None. How point-less specs like stall_heartbeat
        reach the subsystem that implements them (mx.guard,
        mx.serve's slow_client)."""
        rank = _process_index()
        for spec in self._specs:
            if spec["fired"] or spec["kind"] != kind:
                continue
            if spec["rank"] is not None and spec["rank"] != rank:
                continue
            if not spec["every_restart"] and restart_count() > 0:
                continue
            spec["fired"] = True
            return spec["arg"] or ""
        return None

    @staticmethod
    def corrupt_gradient(trainer, step):
        """Deterministic silent data corruption: flip one bit in ONE
        REPLICA (the first addressable device's copy) of the first
        gradient/parameter leaf, as the step's update lands. Flipping a
        single replica — not the logical array — reproduces real SDC
        (one chip computed wrong bytes) and leaves the majority of
        replicas clean, so the mx.guard digest vote can attribute the
        corruption to this rank even in a 2-rank gang (15-vs-1 over an
        8-device mesh pair, not an unresolvable 1-vs-1 tie)."""
        if trainer is None or not hasattr(trainer, "params"):
            return
        import jax
        import numpy as np

        params = trainer.params
        leaf_is_list = isinstance(params, (list, tuple))
        leaf = params[0] if leaf_is_list else params
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            datas = [np.array(s.data) for s in shards]
            buf = datas[0].view(np.uint8).reshape(-1)
            buf[buf.size // 2] ^= 0x10
            arrs = [jax.device_put(d, s.device)
                    for d, s in zip(datas, shards)]
            new = jax.make_array_from_single_device_arrays(
                leaf.shape, leaf.sharding, arrs)
            where = f"replica on device {shards[0].device.id}"
        else:
            data = np.array(leaf)
            buf = data.view(np.uint8).reshape(-1)
            buf[buf.size // 2] ^= 0x10
            new = data
            where = "host copy (no device replicas)"
        if leaf_is_list:
            params[0] = new
        else:
            trainer.params = new
        print(f"mx.resilience: fault injection: corrupt_grad at step "
              f"{step} (rank {_process_index()}): flipped one bit in "
              f"param leaf 0, {where}", file=sys.stderr)

    @staticmethod
    def corrupt_checkpoint(path):
        """Flip bytes in the largest payload file of a written checkpoint
        WITHOUT touching its manifest — exactly the torn-write/bit-rot
        case verify_checkpoint must catch."""
        if not path or not os.path.isdir(path):
            return
        target, size = None, -1
        for rel, full in _walk_files(path):
            if rel == _MANIFEST:
                continue
            s = os.path.getsize(full)
            if s > size:
                target, size = full, s
        if target is None or size == 0:
            return
        with open(target, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(1)
            f.seek(size // 2)
            f.write(bytes([chunk[0] ^ 0xFF if chunk else 0xFF]))
        print(f"mx.resilience: fault injection: corrupted {target}",
              file=sys.stderr)


def fault_point(point, step=None, path=None, trainer=None):
    """Hook production code paths call (only does anything while enabled
    AND a fault_inject spec is armed — the common case is one None
    check)."""
    inj = _injector
    if inj is not None and _enabled:
        inj.fire(point, step=step, path=path, trainer=trainer)


# ---------------------------------------------------------------------------
# estimator checkpointing (epoch-granularity fit-loop state)
# ---------------------------------------------------------------------------

_FIT_STATE = "fit_state.json"


def save_estimator(est, base_dir):
    """Atomic verified checkpoint of an Estimator fit loop: net params,
    gluon-Trainer optimizer state, epoch/batch counters, global RNG.
    Called at epoch boundaries only — a mid-epoch save would be replayed
    against from the epoch's start and double-apply the partial epoch."""
    import jax
    import numpy as np

    from . import random as _random

    epoch = int(est.num_epoch)

    def _writer(tmp):
        est.net.save_parameters(os.path.join(tmp, "net.params"))
        est.trainer.save_states(os.path.join(tmp, "trainer.states"))
        key = np.asarray(jax.random.key_data(_random.get_state()))
        state = {"num_epoch": epoch, "num_batch": int(est.num_batch),
                 "rng_key": [int(x) for x in key.ravel()],
                 "rng_shape": list(key.shape),
                 "rng_dtype": str(key.dtype)}
        with open(os.path.join(tmp, _FIT_STATE), "w") as f:
            json.dump(state, f)
    t0 = time.perf_counter()
    path = RetryPolicy().call(
        write_checkpoint,
        os.path.join(str(base_dir), f"{_STEP_PREFIX}{epoch:010d}"),
        _writer, step=epoch, fingerprint={"trainer": "Estimator"},
        site="checkpoint-io")
    if _telemetry._enabled:
        _M_SAVE_SECONDS.observe(time.perf_counter() - t0)
        _telemetry.event("checkpoint", step=epoch, path=path,
                         dur_s=round(time.perf_counter() - t0, 6))
    _gc_estimator(base_dir)
    return path


def _gc_estimator(base_dir):
    keep = int(_config.get("checkpoint_keep"))
    if keep <= 0 or not _owns_gc():
        return
    _recover_displaced(base_dir)
    for _step, path in list_checkpoints(base_dir)[:-keep]:
        shutil.rmtree(path, ignore_errors=True)


def restore_estimator(est, base_dir, resume="auto"):
    """Restore the newest verified Estimator checkpoint (or the explicit
    `resume` path), falling back past corrupt ones. Returns the restored
    epoch or None. The fit loop then skips already-consumed epochs via
    the restored num_epoch."""
    import numpy as np

    from . import random as _random

    _recover_displaced(base_dir)
    if resume != "auto":
        candidates = [(None, str(resume))]
    else:
        candidates = list(reversed(list_checkpoints(base_dir)))
    fallbacks = 0
    for _step, path in candidates:
        try:
            manifest = verify_checkpoint(path)
            check_fingerprint(manifest, {"trainer": "Estimator"}, path)
            with open(os.path.join(path, _FIT_STATE)) as f:
                state = json.load(f)
            est.net.load_parameters(os.path.join(path, "net.params"))
            est.trainer.load_states(os.path.join(path, "trainer.states"))
        except (CheckpointCorruptError, OSError, ValueError) as e:
            if resume != "auto":
                raise
            fallbacks += 1
            if _telemetry._enabled:
                _M_VERIFY_FAILURES.inc()
            print(f"mx.resilience: rejecting checkpoint: {e} — falling "
                  "back to the previous one", file=sys.stderr)
            continue
        est.num_epoch = int(state["num_epoch"])
        est.num_batch = int(state["num_batch"])
        key = np.asarray(state["rng_key"],
                         dtype=state.get("rng_dtype", "uint32"))
        _random.set_state(key.reshape(state.get("rng_shape", key.shape)))
        _note_resume(path, est.num_epoch, fallbacks)
        return est.num_epoch
    return None


if _config.get("resilience"):
    install()
