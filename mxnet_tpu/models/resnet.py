"""ResNet family (BASELINE.json: GluonCV ResNet-50 images/sec/chip).

Reference: GluonCV / `python/mxnet/gluon/model_zoo/vision/resnet.py`
(BasicBlockV1/V2, BottleneckV1/V2, resnet18..152). NCHW layout at the API;
XLA retiles for the MXU. Train in bf16 with f32 BN statistics by casting the
net (`net.cast('bfloat16')`) — BN computes in f32 internally (ops/nn_ops).
"""
from __future__ import annotations

from ..gluon import nn, HybridBlock
from ..ndarray import ndarray as F

__all__ = ["BasicBlockV1", "BottleneckV1", "ResNetV1", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1",
           "BasicBlockV2", "BottleneckV2", "ResNetV2",
           "resnet18_v2", "resnet34_v2", "resnet50_v2", "resnet101_v2",
           "resnet152_v2"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels,
                     weight_initializer=None)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.ds = nn.HybridSequential()
            self.ds.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                  use_bias=False, in_channels=in_channels))
            self.ds.add(nn.BatchNorm())
        else:
            self.ds = None

    def forward(self, x):
        residual = x if self.ds is None else self.ds(x)
        return F.Activation(self.body(x) + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        mid = channels // 4
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(mid, kernel_size=1, strides=stride, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(mid, 1, mid))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.ds = nn.HybridSequential()
            self.ds.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                  use_bias=False, in_channels=in_channels))
            self.ds.add(nn.BatchNorm())
        else:
            self.ds = None

    def forward(self, x):
        residual = x if self.ds is None else self.ds(x)
        return F.Activation(self.body(x) + residual, act_type="relu")


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        if thumbnail:  # CIFAR-style stem
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            stage = nn.HybridSequential()
            in_c = channels[i]
            stage.add(block(channels[i + 1], stride,
                            downsample=channels[i + 1] != in_c or stride != 1,
                            in_channels=in_c))
            for _ in range(num_layer - 1):
                stage.add(block(channels[i + 1], 1, downsample=False,
                                in_channels=channels[i + 1]))
            self.features.add(stage)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=channels[-1])

    def forward(self, x):
        return self.output(self.features(x))


class BasicBlockV2(HybridBlock):
    """Pre-activation residual block (reference BasicBlockV2, He et al.
    2016 identity mappings): BN-ReLU precedes each conv, and the shortcut
    taps the PRE-activation input."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        self.ds = nn.Conv2D(channels, kernel_size=1, strides=stride,
                            use_bias=False, in_channels=in_channels) \
            if downsample else None

    def forward(self, x):
        act = F.Activation(self.bn1(x), act_type="relu")
        residual = x if self.ds is None else self.ds(act)
        out = self.conv1(act)
        out = self.conv2(F.Activation(self.bn2(out), act_type="relu"))
        return out + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        mid = channels // 4
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(mid, kernel_size=1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(mid, stride, mid)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, use_bias=False)
        self.ds = nn.Conv2D(channels, kernel_size=1, strides=stride,
                            use_bias=False, in_channels=in_channels) \
            if downsample else None

    def forward(self, x):
        act = F.Activation(self.bn1(x), act_type="relu")
        residual = x if self.ds is None else self.ds(act)
        out = self.conv1(act)
        out = self.conv2(F.Activation(self.bn2(out), act_type="relu"))
        out = self.conv3(F.Activation(self.bn3(out), act_type="relu"))
        return out + residual


class ResNetV2(HybridBlock):
    """Pre-activation ResNet (reference ResNetV2): bare stem conv, BN-ReLU
    moved inside blocks, final BN-ReLU before the pool."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(scale=False, center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            stage = nn.HybridSequential()
            in_c = channels[i]
            stage.add(block(channels[i + 1], stride,
                            downsample=channels[i + 1] != in_c or stride != 1,
                            in_channels=in_c))
            for _ in range(num_layer - 1):
                stage.add(block(channels[i + 1], 1, downsample=False,
                                in_channels=channels[i + 1]))
            self.features.add(stage)
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=channels[-1])

    def forward(self, x):
        return self.output(self.features(x))


_SPECS = {
    18: ("basic", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottleneck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottleneck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottleneck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}

_BLOCKS = {(1, "basic"): BasicBlockV1, (1, "bottleneck"): BottleneckV1,
           (2, "basic"): BasicBlockV2, (2, "bottleneck"): BottleneckV2}


def get_resnet(num_layers, classes=1000, version=1, **kwargs):
    kind, layers, channels = _SPECS[num_layers]
    block = _BLOCKS[(version, kind)]
    net_cls = ResNetV1 if version == 1 else ResNetV2
    return net_cls(block, layers, channels, classes=classes, **kwargs)


def resnet18_v1(**kw):
    return get_resnet(18, **kw)


def resnet34_v1(**kw):
    return get_resnet(34, **kw)


def resnet50_v1(**kw):
    return get_resnet(50, **kw)


def resnet101_v1(**kw):
    return get_resnet(101, **kw)


def resnet152_v1(**kw):
    return get_resnet(152, **kw)


def resnet18_v2(**kw):
    return get_resnet(18, version=2, **kw)


def resnet34_v2(**kw):
    return get_resnet(34, version=2, **kw)


def resnet50_v2(**kw):
    return get_resnet(50, version=2, **kw)


def resnet101_v2(**kw):
    return get_resnet(101, version=2, **kw)


def resnet152_v2(**kw):
    return get_resnet(152, version=2, **kw)
