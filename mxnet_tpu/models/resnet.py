"""ResNet family (BASELINE.json: GluonCV ResNet-50 images/sec/chip).

Reference: GluonCV / `python/mxnet/gluon/model_zoo/vision/resnet.py`
(BasicBlockV1/V2, BottleneckV1/V2, resnet18..152). NCHW layout at the API;
XLA retiles for the MXU. Train in bf16 with f32 BN statistics by casting the
net (`net.cast('bfloat16')`) — BN computes in f32 internally (ops/nn_ops).
"""
from __future__ import annotations

from ..gluon import nn, HybridBlock
from ..ndarray import ndarray as F

__all__ = ["BasicBlockV1", "BottleneckV1", "ResNetV1", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels,
                     weight_initializer=None)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.ds = nn.HybridSequential()
            self.ds.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                  use_bias=False, in_channels=in_channels))
            self.ds.add(nn.BatchNorm())
        else:
            self.ds = None

    def forward(self, x):
        residual = x if self.ds is None else self.ds(x)
        return F.Activation(self.body(x) + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        mid = channels // 4
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(mid, kernel_size=1, strides=stride, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(mid, 1, mid))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.ds = nn.HybridSequential()
            self.ds.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                  use_bias=False, in_channels=in_channels))
            self.ds.add(nn.BatchNorm())
        else:
            self.ds = None

    def forward(self, x):
        residual = x if self.ds is None else self.ds(x)
        return F.Activation(self.body(x) + residual, act_type="relu")


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        if thumbnail:  # CIFAR-style stem
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            stage = nn.HybridSequential()
            in_c = channels[i]
            stage.add(block(channels[i + 1], stride,
                            downsample=channels[i + 1] != in_c or stride != 1,
                            in_channels=in_c))
            for _ in range(num_layer - 1):
                stage.add(block(channels[i + 1], 1, downsample=False,
                                in_channels=channels[i + 1]))
            self.features.add(stage)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes, in_units=channels[-1])

    def forward(self, x):
        return self.output(self.features(x))


_SPECS = {
    18: (BasicBlockV1, [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: (BasicBlockV1, [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: (BottleneckV1, [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: (BottleneckV1, [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: (BottleneckV1, [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


def get_resnet(num_layers, classes=1000, **kwargs):
    block, layers, channels = _SPECS[num_layers]
    return ResNetV1(block, layers, channels, classes=classes, **kwargs)


def resnet18_v1(**kw):
    return get_resnet(18, **kw)


def resnet34_v1(**kw):
    return get_resnet(34, **kw)


def resnet50_v1(**kw):
    return get_resnet(50, **kw)


def resnet101_v1(**kw):
    return get_resnet(101, **kw)


def resnet152_v1(**kw):
    return get_resnet(152, **kw)
