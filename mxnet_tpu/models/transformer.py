"""Sockeye-style Transformer NMT (BASELINE.json workload #3).

Reference: Amazon Sockeye (MXNet seq2seq; encoder/decoder transformer with
label smoothing, beam search). TPU-first: flash attention for training
(causal decoder); inference decodes incrementally against a STATIC-shape KV
cache — one jitted step function serves every position (the step index is a
traced scalar, so there is exactly one compile per geometry), with beam
bookkeeping on the host and cache reordering as device-side gathers. No
BucketingModule needed since XLA pads to static shapes anyway.
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn, HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray import NDArray
from ..ndarray import ndarray as F


def _positional_encoding(max_len, units):
    pos = np.arange(max_len)[:, None]
    dim = np.arange(units // 2)[None, :]
    angle = pos / np.power(10000, 2 * dim / units)
    enc = np.zeros((max_len, units), np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        self.q_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                               weight_initializer="xavier")
        self.k_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                               weight_initializer="xavier")
        self.v_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                               weight_initializer="xavier")
        self.out_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                                 weight_initializer="xavier")

    def forward(self, q, kv, mask=None, causal=False):
        B, Lq, E = q.shape
        qh = self._heads_of(self.q_proj, q)
        kh = self._heads_of(self.k_proj, kv)
        vh = self._heads_of(self.v_proj, kv)
        out = F.flash_attention(qh, kh, vh, mask, causal=causal)
        out = out.transpose(axes=(0, 2, 1, 3)).reshape(shape=(B, Lq, E))
        return self.out_proj(out)

    # -- incremental decode (static-shape KV cache) ----------------------
    def _heads_of(self, proj, x):
        B, L, E = x.shape
        H, D = self._heads, self._units // self._heads
        return proj(x).reshape(shape=(B, L, H, D)).transpose(axes=(0, 2, 1, 3))

    def precompute_kv(self, kv):
        """Cross-attention K/V for a fixed memory (encoder output): computed
        once per sequence instead of once per decode step."""
        return self._heads_of(self.k_proj, kv), self._heads_of(self.v_proj, kv)

    def attend_cached(self, x, k_cache, v_cache, mask):
        """One-token attention over cached K/V. x (B,1,E); caches
        (B,H,Lc,D); mask (B,Lc) True=attendable. Plain einsum — decode is
        bandwidth-bound, the MXU tiles don't pay off at Lq=1."""
        import jax
        import jax.numpy as jnp
        from ..ndarray import apply_op

        qh = self._heads_of(self.q_proj, x)                 # (B,H,1,D)

        def att(q, k, v, m):
            D = q.shape[-1]
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) / (D ** 0.5)
            s = jnp.where(m[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) \
                .astype(q.dtype)

        out = apply_op(att, qh, k_cache, v_cache, mask)
        B, E = x.shape[0], self._units
        out = out.transpose(axes=(0, 2, 1, 3)).reshape(shape=(B, 1, E))
        return self.out_proj(out)

    def self_step(self, x, k_cache, v_cache, t):
        """Write this token's K/V at position t, attend over positions <= t.
        Returns (out (B,1,E), new_k, new_v)."""
        from ._decode import cached_self_attention_step

        q = self._heads_of(self.q_proj, x)                  # (B,H,1,D)
        k_new = self._heads_of(self.k_proj, x)
        v_new = self._heads_of(self.v_proj, x)
        o, k_cache, v_cache = cached_self_attention_step(
            q, k_new, v_new, k_cache, v_cache, t)
        return self.out_proj(o), k_cache, v_cache


class TransformerLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 is_decoder=False, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._is_decoder = is_decoder
        self.self_attn = MultiHeadAttention(units, num_heads, dtype)
        self.self_ln = nn.LayerNorm(in_channels=units)
        if is_decoder:
            self.cross_attn = MultiHeadAttention(units, num_heads, dtype)
            self.cross_ln = nn.LayerNorm(in_channels=units)
        self.ffn_in = nn.Dense(hidden_size, in_units=units, flatten=False,
                               dtype=dtype, weight_initializer="xavier")
        self.ffn_out = nn.Dense(units, in_units=hidden_size, flatten=False,
                                dtype=dtype, weight_initializer="xavier")
        self.ffn_ln = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, enc_out=None, self_mask=None, enc_mask=None):
        h = self.self_attn(x, x, mask=self_mask, causal=self._is_decoder)
        if self.dropout:
            h = self.dropout(h)
        x = self.self_ln(x + h)
        if self._is_decoder and enc_out is not None:
            h = self.cross_attn(x, enc_out, mask=enc_mask)
            if self.dropout:
                h = self.dropout(h)
            x = self.cross_ln(x + h)
        h = self.ffn_out(F.Activation(self.ffn_in(x), act_type="relu"))
        if self.dropout:
            h = self.dropout(h)
        return self.ffn_ln(x + h)

    def step(self, x, k_cache, v_cache, t, enc_k, enc_v, enc_mask):
        """One-token decoder step against this layer's KV cache (inference:
        no dropout). Returns (y (B,1,E), new_k, new_v)."""
        h, k_cache, v_cache = self.self_attn.self_step(x, k_cache, v_cache, t)
        x = self.self_ln(x + h)
        h = self.cross_attn.attend_cached(x, enc_k, enc_v, enc_mask)
        x = self.cross_ln(x + h)
        h = self.ffn_out(F.Activation(self.ffn_in(x), act_type="relu"))
        return self.ffn_ln(x + h), k_cache, v_cache


class TransformerNMT(HybridBlock):
    """Encoder-decoder for translation. forward() = teacher-forced training
    scores; `greedy_decode`/`beam_search` for inference."""

    def __init__(self, src_vocab, tgt_vocab, units=512, hidden_size=2048,
                 num_layers=6, num_heads=8, max_length=256, dropout=0.1,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.src_embed = nn.Embedding(src_vocab, units, dtype=dtype,
                                      weight_initializer="xavier")
        self.tgt_embed = nn.Embedding(tgt_vocab, units, dtype=dtype,
                                      weight_initializer="xavier")
        from ..gluon.parameter import Constant
        self.pos_enc = Constant("pos_enc", _positional_encoding(max_length, units))
        self.encoder = nn.HybridSequential()
        for _ in range(num_layers):
            self.encoder.add(TransformerLayer(units, hidden_size, num_heads,
                                              dropout, False, dtype))
        self.decoder = nn.HybridSequential()
        for _ in range(num_layers):
            self.decoder.add(TransformerLayer(units, hidden_size, num_heads,
                                              dropout, True, dtype))
        self.out_proj = nn.Dense(tgt_vocab, in_units=units, flatten=False,
                                 dtype=dtype, weight_initializer="xavier")

    def _embed(self, embed, tokens):
        import jax.numpy as jnp
        x = embed(tokens) * (self._units ** 0.5)
        L = tokens.shape[1]
        return x + NDArray(self.pos_enc.data()._data[:L][None])

    def encode(self, src_tokens, src_valid=None):
        import jax.numpy as jnp
        x = self._embed(self.src_embed, src_tokens)
        mask = None
        if src_valid is not None:
            L = src_tokens.shape[1]
            mask = NDArray(jnp.arange(L)[None, :] <
                           src_valid._data[:, None].astype(jnp.int32))
        for layer in self.encoder:
            x = layer(x, self_mask=mask)
        return x, mask

    def forward(self, src_tokens, tgt_tokens, src_valid=None):
        enc_out, enc_mask = self.encode(src_tokens, src_valid)
        y = self._embed(self.tgt_embed, tgt_tokens)
        for layer in self.decoder:
            y = layer(y, enc_out=enc_out, enc_mask=enc_mask)
        return self.out_proj(y)

    # -- inference -------------------------------------------------------
    def decode_step(self, tok, t, enc_mask, self_k, self_v, enc_k, enc_v):
        """One incremental decode step. tok (B,) int32; t scalar step index
        (traced — one compile serves every step); returns
        (logits (B,V), new_self_k, new_self_v)."""
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray import apply_op

        x = self.tgt_embed(tok.reshape(shape=(-1, 1))) * (self._units ** 0.5)
        pos = apply_op(
            lambda pe, tt: lax.dynamic_slice(
                pe, (tt.astype(jnp.int32), 0), (1, pe.shape[1]))[None],
            NDArray(self.pos_enc.data()._data), t)
        x = x + pos
        new_k, new_v = [], []
        for i, layer in enumerate(self.decoder):
            x, k, v = layer.step(x, self_k[i], self_v[i], t,
                                 enc_k[i], enc_v[i], enc_mask)
            new_k.append(k)
            new_v.append(v)
        logits = self.out_proj(x).reshape(shape=(tok.shape[0], -1))
        return logits, new_k, new_v

    def _init_decode(self, src_tokens, src_valid, beam, max_len):
        """Encode once, precompute cross K/V, allocate self caches, and jit
        the step function (shape-keyed cache: one compile per geometry)."""
        import jax
        import jax.numpy as jnp

        B, Ls = src_tokens.shape
        Bb = B * beam
        enc_out, enc_mask = self.encode(src_tokens, src_valid)
        if enc_mask is None:
            enc_mask = NDArray(jnp.ones((B, Ls), bool))

        def tile(nd):
            return NDArray(jnp.repeat(nd._data, beam, axis=0)) if beam > 1 else nd

        enc_mask = tile(enc_mask)
        enc_k, enc_v = [], []
        for layer in self.decoder:
            k, v = layer.cross_attn.precompute_kv(enc_out)
            enc_k.append(tile(k))
            enc_v.append(tile(v))
        H = self.decoder[0].self_attn._heads
        D = self._units // H
        dt = enc_k[0]._data.dtype
        n = len(self.decoder)
        self_k = [NDArray(jnp.zeros((Bb, H, max_len, D), dt)) for _ in range(n)]
        self_v = [NDArray(jnp.zeros((Bb, H, max_len, D), dt)) for _ in range(n)]

        key = (Bb, Ls, max_len)
        if not hasattr(self, "_decode_cache"):
            self._decode_cache = {}
        if key not in self._decode_cache:
            from ._decode import jit_flat_step
            n_l = n

            def step(tok, t, enc_mask_a, flat):
                logits, nk, nv = self.decode_step(
                    tok, t, enc_mask_a, flat[:n_l], flat[n_l:2 * n_l],
                    flat[2 * n_l:3 * n_l], flat[3 * n_l:])
                return logits, nk + nv   # enc caches are read-only inputs

            # self-attention caches (the leading 2*n_l state entries) are
            # threaded through every step: donate them so the old cache
            # buffers die into the new ones (mx.check `donation-miss`).
            # The encoder K/V (trailing 2*n_l) are READ-ONLY re-passed
            # inputs — never donated
            run_flat = jit_flat_step(self, step, 4 * n_l,
                                     donate_state=2 * n_l)

            def run(tok, t, enc_mask_d, sk, sv, ek, ev):
                logits, state = run_flat(tok, t, enc_mask_d,
                                         sk + sv + ek + ev)
                return logits, state[:n_l], state[n_l:]

            self._decode_cache[key] = run
        run = self._decode_cache[key]
        return (run, enc_mask._data, [k._data for k in enc_k],
                [v._data for v in enc_v],
                [k._data for k in self_k], [v._data for v in self_v])

    def greedy_decode(self, src_tokens, bos=1, eos=2, max_len=None, src_valid=None):
        """KV-cache greedy decode: ONE encoder pass and one jitted O(1)
        step per emitted token (O(L) total; the r1 version re-encoded the
        growing target, O(L^2))."""
        import jax.numpy as jnp
        max_len = max_len or min(self._max_length, 2 * src_tokens.shape[1] + 8)
        B = src_tokens.shape[0]
        run, enc_mask, enc_k, enc_v, self_k, self_v = self._init_decode(
            src_tokens, src_valid, 1, max_len)
        tgt = np.full((B, 1), bos, np.int32)
        finished = np.zeros(B, bool)
        cur = jnp.full((B,), bos, jnp.int32)
        for t in range(max_len - 1):
            logits, self_k, self_v = run(cur, jnp.asarray(t, jnp.int32),
                                         enc_mask, self_k, self_v, enc_k, enc_v)
            nxt = np.asarray(logits.argmax(-1))
            nxt = np.where(finished, eos, nxt)
            finished |= nxt == eos
            tgt = np.concatenate([tgt, nxt[:, None].astype(np.int32)], axis=1)
            if finished.all():
                break
            cur = jnp.asarray(tgt[:, -1], jnp.int32)
        return tgt

    def beam_search(self, src_tokens, beam=4, bos=1, eos=2, max_len=None,
                    src_valid=None, alpha=0.6, return_scores=False):
        """Beam search with KV-cache incremental decode and Sockeye/GNMT
        length normalization lp(l) = ((5+l)/6)^alpha. Returns (B, <=max_len)
        int32 sequences (best beam per batch), or (seqs, scores)."""
        import jax.numpy as jnp

        from ._decode import beam_search_loop

        max_len = max_len or min(self._max_length, 2 * src_tokens.shape[1] + 8)
        B = src_tokens.shape[0]
        run, enc_mask, enc_k, enc_v, self_k, self_v = self._init_decode(
            src_tokens, src_valid, beam, max_len)
        state = {"k": self_k, "v": self_v}

        def dev_step(tok, t):
            logits, state["k"], state["v"] = run(
                jnp.asarray(tok), jnp.asarray(t, jnp.int32),
                enc_mask, state["k"], state["v"], enc_k, enc_v)
            return logits

        def reorder(gather):
            # cross K/V and the encoder mask are beam-invariant: parents
            # stay within a batch
            g = jnp.asarray(gather)
            state["k"] = [jnp.take(c, g, axis=0) for c in state["k"]]
            state["v"] = [jnp.take(c, g, axis=0) for c in state["v"]]

        logits0 = dev_step(np.full((B * beam,), bos, np.int32), 0)
        out, scores = beam_search_loop(
            logits0, lambda tok, i: dev_step(tok, i + 1), reorder,
            B, beam, eos, max_len - 1, alpha=alpha,
            seqs0=np.full((B, beam, 1), bos, np.int32))
        if return_scores:
            return out, scores
        return out


def label_smoothing_loss(logits, labels, smoothing=0.1, pad_id=0):
    """Sockeye-style smoothed CE over NDArrays; ignores pad positions."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import apply_op

    def compute(lg, lbl):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        lbl = lbl.astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, lbl[..., None], -1)[..., 0]
        uniform = -jnp.mean(logp, axis=-1)
        loss = (1 - smoothing) * nll + smoothing * uniform
        keep = (lbl != pad_id).astype(jnp.float32)
        return jnp.sum(loss * keep) / jnp.maximum(jnp.sum(keep), 1.0)

    return apply_op(compute, logits, labels)
