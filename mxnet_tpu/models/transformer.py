"""Sockeye-style Transformer NMT (BASELINE.json workload #3).

Reference: Amazon Sockeye (MXNet seq2seq; encoder/decoder transformer with
label smoothing, beam search). TPU-first: flash attention everywhere
(causal for the decoder), static-shape greedy/beam decode via lax loops —
no BucketingModule needed since XLA pads to static shapes anyway.
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn, HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray import NDArray
from ..ndarray import ndarray as F


def _positional_encoding(max_len, units):
    pos = np.arange(max_len)[:, None]
    dim = np.arange(units // 2)[None, :]
    angle = pos / np.power(10000, 2 * dim / units)
    enc = np.zeros((max_len, units), np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        self.q_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                               weight_initializer="xavier")
        self.k_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                               weight_initializer="xavier")
        self.v_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                               weight_initializer="xavier")
        self.out_proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                                 weight_initializer="xavier")

    def forward(self, q, kv, mask=None, causal=False):
        B, Lq, E = q.shape
        Lk = kv.shape[1]
        H = self._heads
        D = E // H
        qh = self.q_proj(q).reshape(shape=(B, Lq, H, D)).transpose(axes=(0, 2, 1, 3))
        kh = self.k_proj(kv).reshape(shape=(B, Lk, H, D)).transpose(axes=(0, 2, 1, 3))
        vh = self.v_proj(kv).reshape(shape=(B, Lk, H, D)).transpose(axes=(0, 2, 1, 3))
        out = F.flash_attention(qh, kh, vh, mask, causal=causal)
        out = out.transpose(axes=(0, 2, 1, 3)).reshape(shape=(B, Lq, E))
        return self.out_proj(out)


class TransformerLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 is_decoder=False, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._is_decoder = is_decoder
        self.self_attn = MultiHeadAttention(units, num_heads, dtype)
        self.self_ln = nn.LayerNorm(in_channels=units)
        if is_decoder:
            self.cross_attn = MultiHeadAttention(units, num_heads, dtype)
            self.cross_ln = nn.LayerNorm(in_channels=units)
        self.ffn_in = nn.Dense(hidden_size, in_units=units, flatten=False,
                               dtype=dtype, weight_initializer="xavier")
        self.ffn_out = nn.Dense(units, in_units=hidden_size, flatten=False,
                                dtype=dtype, weight_initializer="xavier")
        self.ffn_ln = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, enc_out=None, self_mask=None, enc_mask=None):
        h = self.self_attn(x, x, mask=self_mask, causal=self._is_decoder)
        if self.dropout:
            h = self.dropout(h)
        x = self.self_ln(x + h)
        if self._is_decoder and enc_out is not None:
            h = self.cross_attn(x, enc_out, mask=enc_mask)
            if self.dropout:
                h = self.dropout(h)
            x = self.cross_ln(x + h)
        h = self.ffn_out(F.Activation(self.ffn_in(x), act_type="relu"))
        if self.dropout:
            h = self.dropout(h)
        return self.ffn_ln(x + h)


class TransformerNMT(HybridBlock):
    """Encoder-decoder for translation. forward() = teacher-forced training
    scores; `greedy_decode`/`beam_search` for inference."""

    def __init__(self, src_vocab, tgt_vocab, units=512, hidden_size=2048,
                 num_layers=6, num_heads=8, max_length=256, dropout=0.1,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.src_embed = nn.Embedding(src_vocab, units, dtype=dtype,
                                      weight_initializer="xavier")
        self.tgt_embed = nn.Embedding(tgt_vocab, units, dtype=dtype,
                                      weight_initializer="xavier")
        from ..gluon.parameter import Constant
        self.pos_enc = Constant("pos_enc", _positional_encoding(max_length, units))
        self.encoder = nn.HybridSequential()
        for _ in range(num_layers):
            self.encoder.add(TransformerLayer(units, hidden_size, num_heads,
                                              dropout, False, dtype))
        self.decoder = nn.HybridSequential()
        for _ in range(num_layers):
            self.decoder.add(TransformerLayer(units, hidden_size, num_heads,
                                              dropout, True, dtype))
        self.out_proj = nn.Dense(tgt_vocab, in_units=units, flatten=False,
                                 dtype=dtype, weight_initializer="xavier")

    def _embed(self, embed, tokens):
        import jax.numpy as jnp
        x = embed(tokens) * (self._units ** 0.5)
        L = tokens.shape[1]
        return x + NDArray(self.pos_enc.data()._data[:L][None])

    def encode(self, src_tokens, src_valid=None):
        import jax.numpy as jnp
        x = self._embed(self.src_embed, src_tokens)
        mask = None
        if src_valid is not None:
            L = src_tokens.shape[1]
            mask = NDArray(jnp.arange(L)[None, :] <
                           src_valid._data[:, None].astype(jnp.int32))
        for layer in self.encoder:
            x = layer(x, self_mask=mask)
        return x, mask

    def forward(self, src_tokens, tgt_tokens, src_valid=None):
        enc_out, enc_mask = self.encode(src_tokens, src_valid)
        y = self._embed(self.tgt_embed, tgt_tokens)
        for layer in self.decoder:
            y = layer(y, enc_out=enc_out, enc_mask=enc_mask)
        return self.out_proj(y)

    # -- inference -------------------------------------------------------
    def greedy_decode(self, src_tokens, bos=1, eos=2, max_len=None, src_valid=None):
        """Static-shape greedy decode (re-encodes the growing target each
        step; fine for evaluation; a KV-cache decoder is the perf TODO)."""
        import jax.numpy as jnp
        max_len = max_len or min(self._max_length, 2 * src_tokens.shape[1] + 8)
        B = src_tokens.shape[0]
        enc_out, enc_mask = self.encode(src_tokens, src_valid)
        tgt = np.full((B, 1), bos, np.int32)
        finished = np.zeros(B, bool)
        for _ in range(max_len - 1):
            y = self._embed(self.tgt_embed, NDArray(jnp.asarray(tgt)))
            for layer in self.decoder:
                y = layer(y, enc_out=enc_out, enc_mask=enc_mask)
            logits = self.out_proj(F.slice_axis(y, axis=1, begin=-1, end=None))
            nxt = np.asarray(logits._data.argmax(-1))[:, -1]
            nxt = np.where(finished, eos, nxt)
            finished |= nxt == eos
            tgt = np.concatenate([tgt, nxt[:, None].astype(np.int32)], axis=1)
            if finished.all():
                break
        return tgt


def label_smoothing_loss(logits, labels, smoothing=0.1, pad_id=0):
    """Sockeye-style smoothed CE over NDArrays; ignores pad positions."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import apply_op

    def compute(lg, lbl):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        lbl = lbl.astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, lbl[..., None], -1)[..., 0]
        uniform = -jnp.mean(logp, axis=-1)
        loss = (1 - smoothing) * nll + smoothing * uniform
        keep = (lbl != pad_id).astype(jnp.float32)
        return jnp.sum(loss * keep) / jnp.maximum(jnp.sum(keep), 1.0)

    return apply_op(compute, logits, labels)
