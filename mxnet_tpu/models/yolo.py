"""YOLOv3-tiny (BASELINE workload #4 family; reference: GluonCV
`gluoncv/model_zoo/yolo/yolo3.py` + `src/operator/contrib/` detection ops).

TPU-first choices:
  * static shapes everywhere — gt boxes arrive padded to a fixed max count
    (label -1 rows are padding), target assignment is a vmapped scatter,
    NMS is the static-shape `_contrib_box_nms` registry op;
  * the backbone is plain conv/bn/leaky stacks (MXU-friendly 3x3 convs);
  * decode + loss are pure jax via nd.apply_op, so the whole train step
    jits under ShardedTrainer.

Anchors follow the upstream yolov3-tiny config scaled by `image_size/416`.
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn, HybridBlock
from ..ndarray import NDArray, apply_op
from ..ndarray import ndarray as F

__all__ = ["YOLOv3Tiny", "yolo_targets", "yolo_loss", "decode_predictions"]


def _conv_bn_leaky(channels, kernel=3, stride=1, pad=None):
    pad = (kernel - 1) // 2 if pad is None else pad
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels, kernel, stride, pad, use_bias=False),
            nn.BatchNorm(), nn.LeakyReLU(0.1))
    return blk


class YOLOv3Tiny(HybridBlock):
    """Two-scale tiny YOLOv3. forward -> list of (B, H, W, A, 5+C) raw
    heads, coarse scale first (strides image_size/8 apart by factor 2)."""

    def __init__(self, num_classes=20, image_size=416, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.image_size = image_size
        s = image_size / 416.0
        self.anchors = [
            np.asarray([[81, 82], [135, 169], [344, 319]], np.float32) * s,
            np.asarray([[10, 14], [23, 27], [37, 58]], np.float32) * s,
        ]
        self.strides = [image_size // 13 if image_size % 13 == 0 else 32,
                        image_size // 26 if image_size % 26 == 0 else 16]
        self.na = 3
        c = num_classes + 5

        self.body = nn.HybridSequential()      # -> stride 16 feature
        for ch in (16, 32, 64, 128, 256):
            self.body.add(_conv_bn_leaky(ch))
            if ch != 256:
                self.body.add(nn.MaxPool2D(2, 2))
        self.pool5 = nn.MaxPool2D(2, 2)        # -> stride 32
        self.conv6 = _conv_bn_leaky(512)
        self.conv7 = _conv_bn_leaky(256, kernel=1, pad=0)
        self.head13 = nn.HybridSequential()
        self.head13.add(_conv_bn_leaky(512), nn.Conv2D(self.na * c, 1))
        self.up_conv = _conv_bn_leaky(128, kernel=1, pad=0)
        self.head26 = nn.HybridSequential()
        self.head26.add(_conv_bn_leaky(256), nn.Conv2D(self.na * c, 1))

    def forward(self, x):
        c = self.num_classes + 5
        f16 = self.body(x)                     # (B, 256, H/16, W/16)
        f32 = self.conv7(self.conv6(self.pool5(f16)))
        p13 = self.head13(f32)
        up = self.up_conv(f32)
        up = apply_op(
            lambda a: a.repeat(2, axis=2).repeat(2, axis=3), up)
        p26 = self.head26(F.concat(up, f16, dim=1))

        outs = []
        for p in (p13, p26):
            B, _, H, W = p.shape
            outs.append(p.reshape(shape=(B, self.na, c, H, W))
                        .transpose(axes=(0, 3, 4, 1, 2)))  # (B,H,W,A,5+C)
        return outs


def yolo_targets(model, gt_boxes, gt_labels):
    """Static-shape target assignment. gt_boxes (B, G, 4) corner format in
    image coords, gt_labels (B, G) with -1 padding. Each gt is assigned to
    its best-IoU anchor (by wh overlap, upstream rule) at the cell holding
    the box center. Returns per scale: dict of tobj (B,H,W,A),
    txy (B,H,W,A,2) in-cell offsets, twh (B,H,W,A,2) log-scales,
    tcls (B,H,W,A) int."""
    import jax.numpy as jnp

    sizes = [model.image_size // s for s in model.strides]
    all_anchors = np.concatenate(model.anchors, 0)          # (S*A, 2)

    def one(boxes, labels):
        valid = labels >= 0
        cx = (boxes[:, 0] + boxes[:, 2]) / 2
        cy = (boxes[:, 1] + boxes[:, 3]) / 2
        w = jnp.maximum(boxes[:, 2] - boxes[:, 0], 1e-3)
        h = jnp.maximum(boxes[:, 3] - boxes[:, 1], 1e-3)
        # wh IoU against every anchor (both centered at origin)
        aw, ah = all_anchors[:, 0], all_anchors[:, 1]
        inter = jnp.minimum(w[:, None], aw[None, :]) * \
            jnp.minimum(h[:, None], ah[None, :])
        union = w[:, None] * h[:, None] + aw[None, :] * ah[None, :] - inter
        best = jnp.argmax(inter / union, axis=1)            # (G,)
        scale_of = best // model.na
        anchor_of = best % model.na

        outs = []
        for si, S in enumerate(sizes):
            stride = model.strides[si]
            gx = jnp.clip((cx / stride).astype(jnp.int32), 0, S - 1)
            gy = jnp.clip((cy / stride).astype(jnp.int32), 0, S - 1)
            on = valid & (scale_of == si)
            tobj = jnp.zeros((S, S, model.na))
            txy = jnp.zeros((S, S, model.na, 2))
            twh = jnp.zeros((S, S, model.na, 2))
            tcls = jnp.zeros((S, S, model.na), jnp.int32)
            anc = jnp.asarray(model.anchors[si])
            offx = cx / stride - gx
            offy = cy / stride - gy
            lw = jnp.log(jnp.maximum(w / anc[anchor_of, 0], 1e-6))
            lh = jnp.log(jnp.maximum(h / anc[anchor_of, 1], 1e-6))
            # padded/other-scale gts scatter OUT OF BOUNDS (index S) so
            # mode="drop" discards them (negative indices would wrap)
            gyi = jnp.where(on, gy, S)
            tobj = tobj.at[gyi, gx, anchor_of].set(jnp.where(on, 1.0, 0.0),
                                                   mode="drop")
            txy = txy.at[gyi, gx, anchor_of].set(
                jnp.where(on[:, None], jnp.stack([offx, offy], -1), 0.0),
                mode="drop")
            twh = twh.at[gyi, gx, anchor_of].set(
                jnp.where(on[:, None], jnp.stack([lw, lh], -1), 0.0),
                mode="drop")
            tcls = tcls.at[gyi, gx, anchor_of].set(
                jnp.where(on, labels, 0).astype(jnp.int32), mode="drop")
            outs += [tobj, txy, twh, tcls]
        return tuple(outs)

    import jax
    flat = apply_op(
        lambda b, l: jax.vmap(one)(b.astype(jnp.float32),
                                   l.astype(jnp.int32)),
        gt_boxes, gt_labels)
    out = []
    for si in range(len(sizes)):
        out.append({"obj": flat[4 * si], "xy": flat[4 * si + 1],
                    "wh": flat[4 * si + 2], "cls": flat[4 * si + 3]})
    return out


def yolo_loss(preds, targets, num_classes):
    """GluonCV YOLOV3Loss shape: sigmoid-BCE for center + objectness +
    class, L2 for log-scale wh, all masked to assigned anchors."""
    import jax
    import jax.numpy as jnp

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def one_scale(p, tobj, txy, twh, tcls):
        p = p.astype(jnp.float32)
        obj_logit = p[..., 4]
        obj_loss = bce(obj_logit, tobj).mean()
        mask = tobj[..., None]
        denom = jnp.maximum(tobj.sum(), 1.0)
        xy_loss = (bce(p[..., 0:2], txy) * mask).sum() / denom
        wh_loss = (jnp.square(p[..., 2:4] - twh) * mask).sum() / denom
        cls_1h = jax.nn.one_hot(tcls, num_classes)
        cls_loss = (bce(p[..., 5:], cls_1h) * mask).sum() / denom
        return obj_loss + xy_loss + 0.5 * wh_loss + cls_loss

    total = None
    for p, t in zip(preds, targets):
        part = apply_op(one_scale, p, t["obj"], t["xy"], t["wh"], t["cls"])
        total = part if total is None else total + part
    return total


def decode_predictions(model, preds, conf_thresh=0.1, nms_thresh=0.45,
                       topk=100):
    """Raw heads -> (B, N, 6) rows [class_id, score, x1, y1, x2, y2] after
    per-class NMS (static shape; suppressed rows have score -1)."""
    import jax
    import jax.numpy as jnp

    def one_scale(p, anchors, stride):
        B, H, W, A, _ = p.shape
        p = p.astype(jnp.float32)
        gx = jnp.arange(W)[None, None, :, None]
        gy = jnp.arange(H)[None, :, None, None]
        cx = (jax.nn.sigmoid(p[..., 0]) + gx) * stride
        cy = (jax.nn.sigmoid(p[..., 1]) + gy) * stride
        pw = jnp.exp(jnp.clip(p[..., 2], -8, 8)) * anchors[:, 0]
        ph = jnp.exp(jnp.clip(p[..., 3], -8, 8)) * anchors[:, 1]
        obj = jax.nn.sigmoid(p[..., 4])
        cls = jax.nn.sigmoid(p[..., 5:])
        score = obj[..., None] * cls                       # (B,H,W,A,C)
        cid = jnp.argmax(score, -1).astype(jnp.float32)
        sc = jnp.max(score, -1)
        boxes = jnp.stack([cx - pw / 2, cy - ph / 2,
                           cx + pw / 2, cy + ph / 2], -1)
        rows = jnp.concatenate(
            [cid[..., None], sc[..., None], boxes], -1)    # (B,H,W,A,6)
        return rows.reshape(B, -1, 6)

    parts = []
    for p, anc, s in zip(preds, model.anchors, model.strides):
        parts.append(apply_op(one_scale, p,
                              NDArray(np.asarray(anc, np.float32)),
                              NDArray(np.asarray(s, np.float32))))
    rows = F.concat(*parts, dim=1)
    return F._contrib_box_nms(rows, overlap_thresh=nms_thresh,
                              valid_thresh=conf_thresh, topk=topk,
                              coord_start=2, score_index=1, id_index=0)
