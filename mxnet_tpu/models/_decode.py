"""Shared incremental-decode scaffolding for the autoregressive models
(TransformerNMT beam/greedy decode, GPT generate).

One pattern, one place: wrap a model's `decode_step`-style function in a
throwaway HybridBlock taking flat positional state, functionalize it
(`gluon.functional_call`), `jax.jit` it, and return a runner that re-reads
the model's parameters on every call — parameters are jit ARGUMENTS, not
baked constants, so decoding stays correct after further training."""
from ..gluon import HybridBlock


def cached_self_attention_step(q, k_new, v_new, k_cache, v_cache, t):
    """The one-token causal KV-cache attention inner shared by
    MultiHeadAttention.self_step (NMT) and GPTBlock.step: write this
    token's K/V at position t, attend q over positions <= t.

    q/k_new/v_new (B,H,1,D); caches (B,H,Lmax,D); t traced scalar.
    Returns (out (B,1,H*D), new_k, new_v). Score/softmax/PV math runs in
    float32 regardless of cache dtype (bf16 caches would otherwise give
    decode logits that diverge from the training forward's f32-accumulate
    flash kernel)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ndarray import apply_op

    def f(q_, kn, vn, kc, vc, tt):
        ti = tt.astype(jnp.int32)
        kc = lax.dynamic_update_slice(kc, kn.astype(kc.dtype), (0, 0, ti, 0))
        vc = lax.dynamic_update_slice(vc, vn.astype(vc.dtype), (0, 0, ti, 0))
        B, H, _, D = q_.shape
        s = jnp.einsum("bhqd,bhkd->bhqk", q_.astype(jnp.float32),
                       kc.astype(jnp.float32)) / (D ** 0.5)
        valid = jnp.arange(kc.shape[2])[None, None, None, :] <= ti
        s = jnp.where(valid, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p,
                       vc.astype(jnp.float32)).astype(q_.dtype)
        return o.transpose(0, 2, 1, 3).reshape(B, 1, H * D), kc, vc

    return apply_op(f, q, k_new, v_new, k_cache, v_cache, t)


def batched_cached_attention_step(q, k_new, v_new, k_cache, v_cache, t):
    """`cached_self_attention_step` with PER-ROW positions — the
    continuous-batching variant mx.serve's decode slots need: row b
    writes its K/V at its own position t[b] and attends over positions
    <= t[b]. The math per row is exactly the scalar-t version's
    (f32 score/softmax/PV accumulation), so a request's logits do not
    depend on what the other slots are doing — the property mx.serve's
    bit-identical-under-load guarantee rests on.

    q/k_new/v_new (B,H,1,D); caches (B,H,Lmax,D); t (B,) traced int.
    Returns (out (B,1,H*D), new_k, new_v)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ndarray import apply_op

    def f(q_, kn, vn, kc, vc, tt):
        ti = tt.astype(jnp.int32)                      # (B,)

        def write(c, n, t1):                           # (H,L,D),(H,1,D)
            return lax.dynamic_update_slice(c, n.astype(c.dtype),
                                            (0, t1, 0))

        kc = jax.vmap(write)(kc, kn, ti)
        vc = jax.vmap(write)(vc, vn, ti)
        B, H, _, D = q_.shape
        s = jnp.einsum("bhqd,bhkd->bhqk", q_.astype(jnp.float32),
                       kc.astype(jnp.float32)) / (D ** 0.5)
        valid = jnp.arange(kc.shape[2])[None, None, None, :] \
            <= ti[:, None, None, None]
        s = jnp.where(valid, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p,
                       vc.astype(jnp.float32)).astype(q_.dtype)
        return o.transpose(0, 2, 1, 3).reshape(B, 1, H * D), kc, vc

    return apply_op(f, q, k_new, v_new, k_cache, v_cache, t)


def paged_attention_step(q, k_new, v_new, k_pages, v_pages, tables, wp, wo,
                         t):
    """`batched_cached_attention_step` over an mx.pages block-table
    cache: row b writes this token's K/V into page wp[b] at in-page
    offset wo[b] and attends over positions <= t[b] gathered through its
    page table. The attention math is `pallas_ops.paged_attention`,
    whose XLA fallback is VERBATIM the dense step's f32
    score/softmax/PV expression at the gathered (B,H,L,D) shapes — the
    pages=on bit-identity guarantee composes from there.

    The scatter targets (wp[b], wo[b]) are distinct by construction:
    every serve slot owns its write page exclusively (masked-out rows
    write their private scratch page), so `.at[].set` never sees
    duplicate indices.

    q/k_new/v_new (B,H,1,D); k_pages/v_pages (P,H,ps,D); tables
    (B,n_pg) int32; wp/wo/t (B,) traced int. Returns
    (out (B,1,H*D), new_k_pages, new_v_pages)."""
    import jax.numpy as jnp

    from ..ndarray import apply_op
    from ..pallas_ops import paged_attention as _paged_attn

    def f(q_, kn, vn, kp, vp, tb, wp_, wo_, tt):
        wpi = wp_.astype(jnp.int32)
        woi = wo_.astype(jnp.int32)
        kp = kp.at[wpi, :, woi, :].set(kn[:, :, 0, :].astype(kp.dtype))
        vp = vp.at[wpi, :, woi, :].set(vn[:, :, 0, :].astype(vp.dtype))
        B, H, _, D = q_.shape
        o = _paged_attn(q_, kp, vp, tb.astype(jnp.int32),
                        tt.astype(jnp.int32))
        return o.transpose(0, 2, 1, 3).reshape(B, 1, H * D), kp, vp

    return apply_op(f, q, k_new, v_new, k_pages, v_pages, tables, wp, wo, t)


def beam_search_loop(logits0, step, reorder, B, beam, eos, max_steps,
                     alpha=0.6, seqs0=None, lengths0=1):
    """Host-side beam bookkeeping shared by TransformerNMT.beam_search and
    GPTForCausalLM.generate(num_beams>1): device emits logits, the host
    selects top-k continuations, and `reorder` gathers the KV caches by
    beam parent on-device.

    logits0: (B*beam, V) for the FIRST expansion (encoder bos step for
    NMT, prompt prefill for GPT) — only beam 0 is live so the expansion
    yields `beam` DISTINCT tokens, not copies of the argmax.
    step(tok_flat (B*beam,) int32, i) -> (B*beam, V) logits for expansion
    i+1.  reorder(gather (B*beam,) int32) reindexes the caches.
    Returns (seqs (B, <=max_steps [+ seqs0 cols]), scores (B,)) — the
    best beam per batch under Sockeye/GNMT length norm
    lp(l) = ((5+l)/6)^alpha."""
    import numpy as np

    if seqs0 is None:
        seqs = np.zeros((B, beam, 0), np.int32)
    else:
        seqs = np.asarray(seqs0, np.int32)
    cum = np.full((B, beam), -np.inf, np.float32)
    cum[:, 0] = 0.0
    finished = np.zeros((B, beam), bool)
    lengths = np.full((B, beam), lengths0, np.int32)
    batch_off = np.arange(B)[:, None] * beam
    logits = logits0

    for i in range(max_steps):
        lg = np.asarray(logits, np.float32)
        V = lg.shape[-1]
        m = lg.max(-1, keepdims=True)
        logp = lg - np.log(np.exp(lg - m).sum(-1, keepdims=True)) - m
        logp = logp.reshape(B, beam, V)
        # finished beams may only emit eos, at no additional cost
        fin_row = np.full((V,), -np.inf, np.float32)
        fin_row[eos] = 0.0
        logp = np.where(finished[:, :, None], fin_row[None, None, :], logp)
        flat = (cum[:, :, None] + logp).reshape(B, beam * V)
        top = np.argpartition(-flat, beam - 1, axis=1)[:, :beam]
        order = np.argsort(-np.take_along_axis(flat, top, 1), axis=1)
        top = np.take_along_axis(top, order, 1)              # sorted top-k
        parent = top // V                                    # (B, beam)
        tok = (top % V).astype(np.int32)
        cum = np.take_along_axis(flat, top, 1)
        finished = np.take_along_axis(finished, parent, 1)
        lengths = np.take_along_axis(lengths, parent, 1) + (~finished)
        seqs = np.take_along_axis(seqs, parent[:, :, None], 1)
        seqs = np.concatenate([seqs, tok[:, :, None]], axis=2)
        finished = finished | (tok == eos)
        reorder((batch_off + parent).reshape(-1).astype(np.int32))
        if finished.all():
            break
        if i < max_steps - 1:
            logits = step(tok.reshape(-1).astype(np.int32), i)

    lp = ((5.0 + lengths) / 6.0) ** alpha
    norm = cum / lp
    norm = np.where(np.isfinite(norm), norm, -np.inf)
    best = norm.argmax(axis=1)
    idx = np.arange(B)
    return seqs[idx, best], norm[idx, best]


def jit_flat_step(model, step_fn, n_state, donate_state=0):
    """step_fn(*leading, flat_state: list) -> (primary, new_state: list).

    `model` MUST be the block whose parameters step_fn uses: registering
    it as a child is what makes functional_call substitute its parameters
    as jit ARGUMENTS — without it they trace as closure CONSTANTS and
    decoding silently freezes at the weights of the first compile
    (pinned by tests/train/test_decode.py::test_decode_sees_updated_weights).

    `donate_state`: how many LEADING entries of the flat state are
    threaded through the call (passed in, returned as new state) and
    therefore DONATED to the executable. Without donation every decode
    step double-buffers the whole KV cache — the old buffers stay live
    while XLA allocates the new ones (the mx.check `donation-miss`
    finding that motivated this parameter). Callers must not touch a
    donated buffer after the call: thread the RETURNED state, as both
    decode loops already do. Read-only state entries (e.g. the NMT
    encoder K/V, re-passed every step) go AFTER the donated prefix and
    keep their buffers.

    Returns run(*leading_arrays, state_list) -> (primary, new_state) with
    everything jitted; `leading` are the per-call scalars/arrays before the
    flat state (token ids, step index, masks...). The runner also carries
    `run.aot_exec_peak(*leading_avals, state_avals)` — AOT lower+compile
    at those (shape, dtype)s purely for XLA memory analysis (mx.serve's
    admission control budgets KV-cache growth with it; nothing is
    dispatched and no batch transfers)."""
    import time

    import jax

    from .. import check as _check
    from .. import serve as _serve
    from ..gluon.block import functional_call

    class _Step(HybridBlock):
        def __init__(self):
            super().__init__()
            self.model = model

        def forward(self, *args):
            leading, flat = args[:-n_state], list(args[-n_state:])
            primary, new_state = step_fn(*leading, flat)
            return tuple([primary] + list(new_state))

    pure, gp, aux = functional_call(_Step(), train=False)
    rng = jax.random.key(0)
    # donate_argnums are positional, so the jit is built per leading
    # arity (fixed per call site in practice) on the first call
    cache = {}

    def run(*args):
        leading, state = args[:-1], list(args[-1])
        gp_data = [p.data()._data for _, p in gp]
        aux_data = [p.data()._data for _, p in aux]
        base = 3 + len(leading)     # gp_data, aux_data, rng come first
        donate = tuple(range(base, base + int(donate_state)))
        entry = cache.get(len(leading))
        is_miss = entry is None
        if is_miss:
            entry = cache[len(leading)] = jax.jit(
                pure, donate_argnums=donate)
        if is_miss and _check._enabled:
            try:
                _check.check_jit(
                    f"decode_step({type(model).__name__})",
                    (len(leading), n_state,
                     tuple(tuple(getattr(s, "shape", ())) for s in state)),
                    entry, (gp_data, aux_data, rng) + leading
                    + tuple(state), donate_argnums=donate,
                    can_donate=True)
            except _check.CheckError:
                cache.pop(len(leading), None)
                raise
        if _serve._enabled:
            t0 = time.perf_counter()
            outs, _ = entry(gp_data, aux_data, rng, *leading, *state)
            _serve.note_dispatch(type(model).__name__, t0)
        else:
            outs, _ = entry(gp_data, aux_data, rng, *leading, *state)
        return outs[0], list(outs[1:])

    def aot_exec_peak(*args):
        """Execution-peak bytes (beyond argument buffers) of a call with
        these (shape, dtype) arguments — jax.ShapeDtypeStructs or arrays;
        pure AOT analysis via mx.memsafe, no dispatch, no transfer, and
        nothing installed into the call cache (the real first call still
        runs the mx.check lint; with compile_cache_dir set it
        deserializes this same executable warm). None when the backend
        withholds memory analysis."""
        from .. import memsafe as _memsafe

        leading, state = args[:-1], list(args[-1])
        gp_data = [p.data()._data for _, p in gp]
        aux_data = [p.data()._data for _, p in aux]
        base = 3 + len(leading)
        donate = tuple(range(base, base + int(donate_state)))
        jitted = jax.jit(pure, donate_argnums=donate)

        def aval(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                return x
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)

        full = (gp_data, aux_data, rng) + tuple(aval(a) for a in leading) \
            + tuple(aval(s) for s in state)
        return _memsafe.aot_exec_peak(jitted, full)

    run.aot_exec_peak = aot_exec_peak
    return run
