"""GPT-2-style decoder-only causal language model.

Reference surface: the GluonNLP model zoo's text-generation family
(`gpt2_117m`/`gpt2_345m`, upstream gluon-nlp `scripts/text_generation/`,
model code `gluonnlp/model/transformer.py` GPT-2 variant) — the
reference ecosystem's causal-LM counterpart to BERT.  TPU-first build:
pre-LN blocks over the same fused-QKV flash attention as BERT but
`causal=True`, composing with every parallel axis this framework has —
dp/fsdp via ShardedTrainer, tp via `tp_rules`, ring/Ulysses sequence
parallelism for long context (`gpt_long_config`, SURVEY §5.7), and
`scan_layers` compile-once depth scaling shared with BERT.

The LM head ties the token embedding (GPT-2 has no separate output
matrix and no head bias).
"""
import numpy as np

from ..gluon import nn, HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray import NDArray
from ..ndarray import ndarray as F
from .bert import BERTAttention, _positions, _scan_layers_call
from .bert import tp_rules as _bert_tp_rules


def gpt2_117m_config(**overrides):
    cfg = dict(vocab_size=50257, units=768, hidden_size=3072, num_layers=12,
               num_heads=12, max_length=1024, dropout=0.1, attn_dropout=0.0,
               seq_parallel=False, dtype="float32", remat=False,
               scan_layers=False)
    cfg.update(overrides)
    return cfg


def gpt2_345m_config(**overrides):
    # medium: same scan-once + remat depth treatment as bert_large
    cfg = gpt2_117m_config(units=1024, hidden_size=4096, num_layers=24,
                           num_heads=16, remat=True, scan_layers=True)
    cfg.update(overrides)
    return cfg


def gpt_long_config(**overrides):
    """Long-context causal pretraining: sequence sharded over the mesh's
    `sp` axis with CAUSAL ring attention (SURVEY §5.7)."""
    cfg = gpt2_117m_config(max_length=8192, seq_parallel=True, remat=True,
                           scan_layers=True)
    cfg.update(overrides)
    return cfg


def gpt_tiny_config(**overrides):
    cfg = gpt2_117m_config(vocab_size=128, units=64, hidden_size=128,
                           num_layers=2, num_heads=4, max_length=64,
                           dropout=0.0)
    cfg.update(overrides)
    return cfg


class GPTBlock(HybridBlock):
    """Pre-LN decoder block (GPT-2 ordering: LN -> attn -> +res,
    LN -> MLP -> +res)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32", attn_dropout=0.0, seq_parallel=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.attn = BERTAttention(units, num_heads, attn_dropout, dtype,
                                  seq_parallel=seq_parallel, causal=True)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn_in = nn.Dense(hidden_size, in_units=units, flatten=False,
                               dtype=dtype, weight_initializer="xavier")
        self.ffn_out = nn.Dense(units, in_units=hidden_size, flatten=False,
                                dtype=dtype, weight_initializer="xavier")
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        a = self.attn(self.ln1(x), mask)
        if self.dropout:
            a = self.dropout(a)
        x = x + a
        h = self.ffn_out(F.Activation(self.ffn_in(self.ln2(x)),
                                      act_type="gelu"))
        if self.dropout:
            h = self.dropout(h)
        return x + h


class GPTModel(HybridBlock):
    """Token+position embeddings -> pre-LN block stack -> final LN.
    Returns hidden states (B, L, E)."""

    def __init__(self, vocab_size, units, hidden_size, num_layers, num_heads,
                 max_length=1024, dropout=0.1, attn_dropout=0.0,
                 seq_parallel=False, dtype="float32", remat=False,
                 scan_layers=False, **kwargs):
        super().__init__(**kwargs)
        self._remat = remat
        self._scan_layers = scan_layers
        self._seq_parallel = seq_parallel
        self.word_embed = nn.Embedding(vocab_size, units, dtype=dtype,
                                       weight_initializer="xavier")
        self.position_embed = Parameter(
            "position_weight", shape=(max_length, units), dtype=dtype,
            init="xavier")
        self.position_embed.shard_hint = "embedding"
        self.embed_dropout = nn.Dropout(dropout) if dropout else None
        self.layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.layers.add(GPTBlock(units, hidden_size, num_heads, dropout,
                                     dtype, attn_dropout=attn_dropout,
                                     seq_parallel=seq_parallel))
        self.ln_f = nn.LayerNorm(in_channels=units)

    def forward(self, inputs, valid_length=None):
        B, L = inputs.shape
        from ..parallel import in_manual
        sp_manual = self._seq_parallel and in_manual("sp")
        x = self.word_embed(inputs)
        x = x + _positions(self.position_embed, L, sp_manual).expand_dims(
            axis=0)
        if self.embed_dropout:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            import jax
            import jax.numpy as jnp
            vl = valid_length._data if isinstance(valid_length, NDArray) \
                else valid_length
            idx = jnp.arange(L)
            if sp_manual:
                idx = idx + jax.lax.axis_index("sp") * L
            mask = NDArray(idx[None, :] < vl[:, None].astype(jnp.int32))
        if self._seq_parallel and not sp_manual:
            from ..ndarray import apply_op
            from ..parallel import specs as _sp
            x = apply_op(_sp.constrain_seq, x)
        from .. import _engine
        use_remat = self._remat and not _engine.is_recording()
        if self._scan_layers and not _engine.is_recording():
            x = _scan_layers_call(list(self.layers), x, mask, use_remat)
        else:
            from .bert import _remat_call
            for layer in self.layers:
                if use_remat:
                    x = _remat_call(layer, x, mask)
                else:
                    x = layer(x, mask)
        # pin to batch sharding before the tied-embedding head: same
        # rationale as BERTModel — the head matmul against fsdp-sharded
        # word_embed weights otherwise propagates conflicting feature
        # shardings onto d(hidden), which GSPMD resolves by full remat
        from ..ndarray import apply_op
        from ..parallel import specs as _specs
        x = apply_op(_specs.constrain_batch, x)
        return self.ln_f(x)


class GPTForCausalLM(HybridBlock):
    """Hidden states -> tied-embedding logits (B, L, V)."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        self.gpt = GPTModel(**cfg)

    def forward(self, inputs, valid_length=None):
        import jax.numpy as jnp
        from ..ndarray import apply_op

        h = self.gpt(inputs, valid_length)
        return apply_op(lambda hh, w: jnp.matmul(hh, w.T.astype(hh.dtype)),
                        h, self.gpt.word_embed.weight.data())


def gpt_lm_loss(logits, labels, weights):
    """Next-token cross entropy on NDArrays (ShardedTrainer loss_fn and
    eager compatible). logits (B, L, V) at input positions, labels (B, L)
    the NEXT token at each position (pre-shifted by the data pipeline so
    sequence-parallel shards stay self-contained), weights (B, L) 0/1."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import apply_op

    def compute(lg, lb, w):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(
            logp, lb.astype(jnp.int32)[..., None], -1)[..., 0]
        w = w.astype(jnp.float32)
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)

    return apply_op(compute, logits, labels, weights)


def make_synthetic_batch(cfg, batch_size, seq_len, seed=0):
    """Tokens + pre-shifted next-token labels + weights, numpy."""
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, cfg["vocab_size"],
                       (batch_size, seq_len + 1)).astype(np.int32)
    return {
        "input_ids": toks[:, :-1],
        "labels": toks[:, 1:],
        "weights": np.ones((batch_size, seq_len), np.float32),
        "valid_length": np.full((batch_size,), seq_len, np.int32),
    }


def tp_rules(tp_axis="tp"):
    """Megatron sharding for GPT params: bert.tp_rules verbatim (the block
    param names match by construction) plus the position table on its
    feature dim — the tied LM head then contracts over the sharded dim
    with a psum."""
    from jax.sharding import PartitionSpec as P
    return _bert_tp_rules(tp_axis) + [(r"position_weight$", P(None, tp_axis))]
