"""GPT-2-style decoder-only causal language model.

Reference surface: the GluonNLP model zoo's text-generation family
(`gpt2_117m`/`gpt2_345m`, upstream gluon-nlp `scripts/text_generation/`,
model code `gluonnlp/model/transformer.py` GPT-2 variant) — the
reference ecosystem's causal-LM counterpart to BERT.  TPU-first build:
pre-LN blocks over the same fused-QKV flash attention as BERT but
`causal=True`, composing with every parallel axis this framework has —
dp/fsdp via ShardedTrainer, tp via `tp_rules`, ring/Ulysses sequence
parallelism for long context (`gpt_long_config`, SURVEY §5.7), and
`scan_layers` compile-once depth scaling shared with BERT.

The LM head ties the token embedding (GPT-2 has no separate output
matrix and no head bias).
"""
import numpy as np

from ..gluon import nn, HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray import NDArray
from ..ndarray import ndarray as F
from .bert import BERTAttention, _positions, _scan_layers_call
from .bert import tp_rules as _bert_tp_rules


def gpt2_117m_config(**overrides):
    cfg = dict(vocab_size=50257, units=768, hidden_size=3072, num_layers=12,
               num_heads=12, max_length=1024, dropout=0.1, attn_dropout=0.0,
               seq_parallel=False, dtype="float32", remat=False,
               scan_layers=False)
    cfg.update(overrides)
    return cfg


def gpt2_345m_config(**overrides):
    # medium: same scan-once + remat depth treatment as bert_large
    cfg = gpt2_117m_config(units=1024, hidden_size=4096, num_layers=24,
                           num_heads=16, remat=True, scan_layers=True)
    cfg.update(overrides)
    return cfg


def gpt_long_config(**overrides):
    """Long-context causal pretraining: sequence sharded over the mesh's
    `sp` axis with CAUSAL ring attention (SURVEY §5.7)."""
    cfg = gpt2_117m_config(max_length=8192, seq_parallel=True, remat=True,
                           scan_layers=True)
    cfg.update(overrides)
    return cfg


def gpt_tiny_config(**overrides):
    cfg = gpt2_117m_config(vocab_size=128, units=64, hidden_size=128,
                           num_layers=2, num_heads=4, max_length=64,
                           dropout=0.0)
    cfg.update(overrides)
    return cfg


class GPTBlock(HybridBlock):
    """Pre-LN decoder block (GPT-2 ordering: LN -> attn -> +res,
    LN -> MLP -> +res)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32", attn_dropout=0.0, seq_parallel=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.attn = BERTAttention(units, num_heads, attn_dropout, dtype,
                                  seq_parallel=seq_parallel, causal=True)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn_in = nn.Dense(hidden_size, in_units=units, flatten=False,
                               dtype=dtype, weight_initializer="xavier")
        self.ffn_out = nn.Dense(units, in_units=hidden_size, flatten=False,
                                dtype=dtype, weight_initializer="xavier")
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        a = self.attn(self.ln1(x), mask)
        if self.dropout:
            a = self.dropout(a)
        x = x + a
        h = self.ffn_out(F.Activation(self.ffn_in(self.ln2(x)),
                                      act_type="gelu"))
        if self.dropout:
            h = self.dropout(h)
        return x + h

    def prefill(self, x, k_cache, v_cache):
        """Full-prompt forward that ALSO writes K/V[0:Lp] into the caches:
        on-device prefill is one batched (flash-attention) pass instead of
        Lp sequential one-token steps. x (B, Lp, E); caches (B,H,Lmax,D).
        Returns (y, new_k, new_v)."""
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray import apply_op

        attn = self.attn
        H = attn._num_heads
        qkv = attn.qkv(self.ln1(x))             # (B, Lp, 3E)
        B, Lp, E3 = qkv.shape
        D = E3 // 3 // H

        def split_write(qkv_d, kc, vc):
            r = qkv_d.reshape(B, Lp, 3, H, D)
            q = r[:, :, 0].transpose(0, 2, 1, 3)
            k = r[:, :, 1].transpose(0, 2, 1, 3)
            v = r[:, :, 2].transpose(0, 2, 1, 3)
            kc = lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                          (0, 0, 0, 0))
            vc = lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                          (0, 0, 0, 0))
            return q, k, v, kc, vc

        q, k, v, k_cache, v_cache = apply_op(split_write, qkv, k_cache,
                                             v_cache)
        o = F.flash_attention(q, k, v, None, causal=True)   # (B,H,Lp,D)
        o = o.transpose(axes=(0, 2, 1, 3)).reshape(shape=(B, Lp, H * D))
        x = x + attn.proj(o)
        h = self.ffn_out(F.Activation(self.ffn_in(self.ln2(x)),
                                      act_type="gelu"))
        return x + h, k_cache, v_cache

    def step(self, x, k_cache, v_cache, t):
        """One-token incremental step against a static-shape KV cache
        (inference; same scheme as transformer.TransformerLayer.step).
        x (B,1,E); caches (B,H,Lmax,D); t traced scalar — one compile
        serves every position."""
        from ..ndarray import apply_op
        from ._decode import cached_self_attention_step

        attn = self.attn
        H = attn._num_heads
        qkv = attn.qkv(self.ln1(x))             # (B, 1, 3E)
        B, _, E3 = qkv.shape
        D = E3 // 3 // H

        def split(qkv_d):
            r = qkv_d.reshape(B, 1, 3, H, D)
            return (r[:, :, 0].transpose(0, 2, 1, 3),
                    r[:, :, 1].transpose(0, 2, 1, 3),
                    r[:, :, 2].transpose(0, 2, 1, 3))   # (B,H,1,D) each

        q, k_new, v_new = apply_op(split, qkv)
        o, k_cache, v_cache = cached_self_attention_step(
            q, k_new, v_new, k_cache, v_cache, t)
        x = x + attn.proj(o)
        h2 = self.ffn_out(F.Activation(self.ffn_in(self.ln2(x)),
                                       act_type="gelu"))
        return x + h2, k_cache, v_cache

    def step_slots(self, x, k_cache, v_cache, t):
        """`step` with PER-SLOT positions t (B,) — the mx.serve
        continuous-batching variant: each batch row is an independent
        request at its own decode position. Row math is identical to
        `step`'s, so a row's output never depends on its neighbors."""
        from ..ndarray import apply_op
        from ._decode import batched_cached_attention_step

        attn = self.attn
        H = attn._num_heads
        qkv = attn.qkv(self.ln1(x))             # (B, 1, 3E)
        B, _, E3 = qkv.shape
        D = E3 // 3 // H

        def split(qkv_d):
            r = qkv_d.reshape(B, 1, 3, H, D)
            return (r[:, :, 0].transpose(0, 2, 1, 3),
                    r[:, :, 1].transpose(0, 2, 1, 3),
                    r[:, :, 2].transpose(0, 2, 1, 3))   # (B,H,1,D) each

        q, k_new, v_new = apply_op(split, qkv)
        o, k_cache, v_cache = batched_cached_attention_step(
            q, k_new, v_new, k_cache, v_cache, t)
        x = x + attn.proj(o)
        h2 = self.ffn_out(F.Activation(self.ffn_in(self.ln2(x)),
                                       act_type="gelu"))
        return x + h2, k_cache, v_cache

    def step_slots_paged(self, x, k_pages, v_pages, tables, wp, wo, t):
        """`step_slots` against an mx.pages block-table cache: the K/V
        write lands in page wp[b] offset wo[b] instead of a dense slot
        row, and attention gathers through tables (B,n_pg). Everything
        around the cache access — qkv projection, split, proj, FFN — is
        VERBATIM `step_slots`, and `paged_attention_step`'s fallback is
        the dense step's attention math at the gathered shapes, which is
        what makes pages=on serving bit-identical to pages=off."""
        from ..ndarray import apply_op
        from ._decode import paged_attention_step

        attn = self.attn
        H = attn._num_heads
        qkv = attn.qkv(self.ln1(x))             # (B, 1, 3E)
        B, _, E3 = qkv.shape
        D = E3 // 3 // H

        def split(qkv_d):
            r = qkv_d.reshape(B, 1, 3, H, D)
            return (r[:, :, 0].transpose(0, 2, 1, 3),
                    r[:, :, 1].transpose(0, 2, 1, 3),
                    r[:, :, 2].transpose(0, 2, 1, 3))   # (B,H,1,D) each

        q, k_new, v_new = apply_op(split, qkv)
        o, k_pages, v_pages = paged_attention_step(
            q, k_new, v_new, k_pages, v_pages, tables, wp, wo, t)
        x = x + attn.proj(o)
        h2 = self.ffn_out(F.Activation(self.ffn_in(self.ln2(x)),
                                       act_type="gelu"))
        return x + h2, k_pages, v_pages


class GPTModel(HybridBlock):
    """Token+position embeddings -> pre-LN block stack -> final LN.
    Returns hidden states (B, L, E)."""

    # remat policies route here (see BERTModel): per-layer / scan-body
    # checkpointing per the mx.memsafe graduated policy; the legacy
    # `remat=True` config flag stays the "layers" alias
    _remat_handles_policy = True

    def __init__(self, vocab_size, units, hidden_size, num_layers, num_heads,
                 max_length=1024, dropout=0.1, attn_dropout=0.0,
                 seq_parallel=False, dtype="float32", remat=False,
                 scan_layers=False, **kwargs):
        super().__init__(**kwargs)
        self._remat = remat
        self._scan_layers = scan_layers
        self._seq_parallel = seq_parallel
        self.word_embed = nn.Embedding(vocab_size, units, dtype=dtype,
                                       weight_initializer="xavier")
        self.position_embed = Parameter(
            "position_weight", shape=(max_length, units), dtype=dtype,
            init="xavier")
        self.position_embed.shard_hint = "embedding"
        self.embed_dropout = nn.Dropout(dropout) if dropout else None
        self.layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.layers.add(GPTBlock(units, hidden_size, num_heads, dropout,
                                     dtype, attn_dropout=attn_dropout,
                                     seq_parallel=seq_parallel))
        self.ln_f = nn.LayerNorm(in_channels=units)

    def forward(self, inputs, valid_length=None):
        B, L = inputs.shape
        from ..parallel import in_manual
        sp_manual = self._seq_parallel and in_manual("sp")
        x = self.word_embed(inputs)
        x = x + _positions(self.position_embed, L, sp_manual).expand_dims(
            axis=0)
        if self.embed_dropout:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            import jax
            import jax.numpy as jnp
            vl = valid_length._data if isinstance(valid_length, NDArray) \
                else valid_length
            idx = jnp.arange(L)
            if sp_manual:
                idx = idx + jax.lax.axis_index("sp") * L
            mask = NDArray(idx[None, :] < vl[:, None].astype(jnp.int32))
        if self._seq_parallel and not sp_manual:
            from ..ndarray import apply_op
            from ..parallel import specs as _sp
            x = apply_op(_sp.constrain_seq, x)
        from .. import _engine
        from .. import memsafe as _memsafe
        policy = _memsafe.effective_policy(
            getattr(self, "_remat_policy", None), self._remat)
        if _engine.is_recording():
            policy = "none"
        if self._scan_layers and not _engine.is_recording():
            x = _scan_layers_call(list(self.layers), x, mask, policy)
        else:
            from .bert import _stack_call
            x = _stack_call(list(self.layers), x, mask, policy)
        # pin to batch sharding before the tied-embedding head: same
        # rationale as BERTModel — the head matmul against fsdp-sharded
        # word_embed weights otherwise propagates conflicting feature
        # shardings onto d(hidden), which GSPMD resolves by full remat
        from ..ndarray import apply_op
        from ..parallel import specs as _specs
        x = apply_op(_specs.constrain_batch, x)
        return self.ln_f(x)


class GPTForCausalLM(HybridBlock):
    """Hidden states -> tied-embedding logits (B, L, V)."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        self.gpt = GPTModel(**cfg)

    def forward(self, inputs, valid_length=None):
        import jax.numpy as jnp
        from ..ndarray import apply_op

        h = self.gpt(inputs, valid_length)
        return apply_op(lambda hh, w: jnp.matmul(hh, w.T.astype(hh.dtype)),
                        h, self.gpt.word_embed.weight.data())

    # -- incremental generation (static-shape KV cache) -------------------
    def decode_step(self, tok, t, self_k, self_v):
        """One incremental step: tok (B,) int32, t traced scalar position;
        returns (logits (B,V), new_self_k, new_self_v). Same scheme as
        transformer.TransformerNMT.decode_step — one compile serves every
        position, including the prompt prefill."""
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray import apply_op

        g = self.gpt
        x = g.word_embed(tok.reshape(shape=(-1, 1)))
        pos = apply_op(
            lambda pe, tt: lax.dynamic_slice(
                pe, (tt.astype(jnp.int32), 0), (1, pe.shape[1]))[None],
            NDArray(g.position_embed.data()._data), t)
        x = x + pos
        new_k, new_v = [], []
        for i, layer in enumerate(g.layers):
            x, k, v = layer.step(x, self_k[i], self_v[i], t)
            new_k.append(k)
            new_v.append(v)
        x = g.ln_f(x)
        logits = apply_op(
            lambda hh, w: jnp.matmul(hh, w.T.astype(hh.dtype)),
            x, g.word_embed.weight.data())
        return logits.reshape(shape=(tok.shape[0], -1)), new_k, new_v

    def decode_step_slots(self, tok, t, self_k, self_v):
        """`decode_step` with PER-SLOT positions: tok (B,) int32, t (B,)
        traced int32 — batch row b is an independent request at its own
        position t[b] (mx.serve's continuous-batching decode). Returns
        (logits (B,V), new_self_k, new_self_v); one compile serves every
        position mix in a (B, cache-length) bucket."""
        import jax.numpy as jnp
        from ..ndarray import apply_op

        g = self.gpt
        x = g.word_embed(tok.reshape(shape=(-1, 1)))
        pos = apply_op(
            lambda pe, tt: pe[tt.astype(jnp.int32)][:, None, :],
            NDArray(g.position_embed.data()._data), t)
        x = x + pos
        new_k, new_v = [], []
        for i, layer in enumerate(g.layers):
            x, k, v = layer.step_slots(x, self_k[i], self_v[i], t)
            new_k.append(k)
            new_v.append(v)
        x = g.ln_f(x)
        logits = apply_op(
            lambda hh, w: jnp.matmul(hh, w.T.astype(hh.dtype)),
            x, g.word_embed.weight.data())
        return logits.reshape(shape=(tok.shape[0], -1)), new_k, new_v

    # -- paged decode (mx.pages block-table cache) -------------------------
    def _paged_token_step(self, tok_d, pos_d, tb_d, wp_d, wo_d, ks, vs):
        """Raw-jax one-token paged step (the lax.scan body of the chunk
        and draft programs): the EXACT `decode_step_slots` computation —
        embed + pe[pos] + layer stack + ln_f + tied logits — with the
        layers' cache access routed through `step_slots_paged`. Takes and
        returns raw arrays (scan carries); ks/vs are tuples of the
        pooled (P,H,ps,D) page arrays per layer.

        Returns (f32 logits (B,V), new_ks, new_vs)."""
        import jax.numpy as jnp
        from ..ndarray import apply_op

        g = self.gpt
        tok = NDArray(tok_d)
        t = NDArray(pos_d)
        x = g.word_embed(tok.reshape(shape=(-1, 1)))
        pos = apply_op(
            lambda pe, tt: pe[tt.astype(jnp.int32)][:, None, :],
            NDArray(g.position_embed.data()._data), t)
        x = x + pos
        nk, nv = [], []
        for i, layer in enumerate(g.layers):
            x, k, v = layer.step_slots_paged(
                x, NDArray(ks[i]), NDArray(vs[i]), NDArray(tb_d),
                NDArray(wp_d), NDArray(wo_d), t)
            nk.append(k._data)
            nv.append(v._data)
        x = g.ln_f(x)
        logits = apply_op(
            lambda hh, w: jnp.matmul(hh, w.T.astype(hh.dtype)),
            x, g.word_embed.weight.data())
        lg = logits.reshape(shape=(tok.shape[0], -1))._data \
            .astype(jnp.float32)
        return lg, tuple(nk), tuple(nv)

    def _paged_write_targets(self, pos_d, active_d, tb_d, page_size):
        """Write page/offset for one chunk step: active rows write page
        tables[b, pos//ps] at offset pos%ps; masked rows write their
        private scratch page (page id == batch row — mx.pages reserves
        pages 0..slots-1 as per-slot scratch), so a batched step never
        scatters two rows into one (page, offset) cell and never pollutes
        a real page of an inactive request. Positions past the table's
        range also divert to scratch: a speculative round that starts
        near the bucket's last position feeds its fixed k+1 tokens past
        the end, and clipping those writes back into the last real page
        would corrupt positions the row still attends."""
        import jax.numpy as jnp

        B, n_pg = tb_d.shape
        idx = jnp.clip(pos_d // page_size, 0, n_pg - 1)
        real = jnp.take_along_axis(tb_d, idx[:, None], axis=1)[:, 0]
        scratch = jnp.arange(B, dtype=jnp.int32)
        ok = active_d & (pos_d < n_pg * page_size)
        wp = jnp.where(ok, real.astype(jnp.int32), scratch)
        wo = jnp.where(ok, pos_d % page_size, 0).astype(jnp.int32)
        return wp, wo

    def decode_paged_chunk(self, toks, t0, n, tables, flat, page_size,
                           full=False):
        """Chunked paged decode body (jit_flat_step step_fn): row b feeds
        its n[b] tokens toks[b, :n[b]] at positions t0[b].. — many prompt
        tokens per dispatch (batched prefill) or one (steady decode), in
        ONE executable per (bucket, chunk) shape. The body is a lax.scan
        of C structurally identical one-token steps, each exactly the
        dense `decode_step_slots` computation, so a chunk's logits are
        bit-identical to feeding the same tokens one dispatch at a time.

        Rows past their count (j >= n[b]) run masked: writes land in the
        row's scratch page and their logits are discarded — mirroring the
        dense path's harmless pad-slot pollution argument.

        toks (B,C) int32; t0/n (B,) int32; tables (B,n_pg) int32; flat =
        2*n_l pooled page arrays (K per layer, then V). Returns
        (last-active f32 logits (B,V) — or the full (B,C,V) stack when
        `full`, the speculative verify surface — and the new pool
        arrays)."""
        import jax
        import jax.numpy as jnp

        n_l = len(self.gpt.layers)
        toks_d, t0_d, n_d, tb_d = (toks._data, t0._data, n._data,
                                   tables._data)
        flat_d = [f._data for f in flat]
        B, C = toks_d.shape
        V = self.gpt.word_embed.weight.shape[0]

        def tok_step(carry, j):
            ks, vs, last = carry
            tokj = jax.lax.dynamic_index_in_dim(
                toks_d, j, axis=1, keepdims=False).astype(jnp.int32)
            pos = (t0_d + j).astype(jnp.int32)
            active = j < n_d
            wp, wo = self._paged_write_targets(pos, active, tb_d,
                                               page_size)
            lg, ks, vs = self._paged_token_step(tokj, pos, tb_d, wp, wo,
                                                ks, vs)
            last = jnp.where((j == n_d - 1)[:, None], lg, last)
            return (ks, vs, last), (lg if full else jnp.zeros((), lg.dtype))

        last0 = jnp.zeros((B, V), jnp.float32)
        (ks, vs, last), stack = jax.lax.scan(
            tok_step, (tuple(flat_d[:n_l]), tuple(flat_d[n_l:]), last0),
            jnp.arange(C))
        out = stack.transpose(1, 0, 2) if full else last   # (B,C,V)|(B,V)
        return NDArray(out), [NDArray(a) for a in list(ks) + list(vs)]

    def decode_paged_draft(self, tok0, t0, active, tables, flat, page_size,
                           n_draft):
        """Greedy draft chain (jit_flat_step step_fn on the DRAFTER
        model): feed tok0[b] at position t0[b], take the argmax as the
        next token, repeat — n_draft proposals in one dispatch. The
        drafter writes its own pooled page arrays (`flat`, the pool's
        'draft' stream) through the SAME page tables as the target, so a
        prefix-tree hit skips drafter prefill too.

        Inactive rows (active[b] False — row not in a speculative round)
        run fully masked into scratch. Proposals feed exact-acceptance
        verification (arxiv 2302.01318): the target checks them in one
        chunked step and keeps the longest agreeing prefix, so a wrong
        draft costs speed, never correctness.

        tok0/t0 (B,) int32; active (B,) bool; tables (B,n_pg) int32.
        Returns (drafts (B, n_draft) int32, new draft-pool arrays)."""
        import jax
        import jax.numpy as jnp

        n_l = len(self.gpt.layers)
        tok0_d, t0_d, act_d, tb_d = (tok0._data, t0._data, active._data,
                                     tables._data)
        flat_d = [f._data for f in flat]

        def tok_step(carry, i):
            ks, vs, tok = carry
            pos = (t0_d + i).astype(jnp.int32)
            wp, wo = self._paged_write_targets(pos, act_d, tb_d, page_size)
            lg, ks, vs = self._paged_token_step(tok, pos, tb_d, wp, wo,
                                                ks, vs)
            nxt = jnp.argmax(lg, -1).astype(jnp.int32)
            return (ks, vs, nxt), nxt

        (ks, vs, _), drafts = jax.lax.scan(
            tok_step, (tuple(flat_d[:n_l]), tuple(flat_d[n_l:]),
                       tok0_d.astype(jnp.int32)),
            jnp.arange(n_draft))
        return NDArray(drafts.T), [NDArray(a) for a in list(ks) + list(vs)]

    def _init_generate(self, B, max_len):
        """Allocate caches and jit the step (shape-keyed — the reference
        analog is gluonnlp's SequenceSampler over a hybridized decoder)."""
        import jax
        import jax.numpy as jnp

        n_l = len(self.gpt.layers)
        caches = self._alloc_caches(B, max_len)
        self_k, self_v = caches[:n_l], caches[n_l:]

        key = (B, max_len)
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            from ._decode import jit_flat_step

            def step(tok, t, flat):
                logits, nk, nv = self.decode_step(
                    tok, t, flat[:n_l], flat[n_l:])
                return logits, nk + nv

            # the K/V caches are threaded through every step: donate them
            # (old cache buffers die into the new ones instead of
            # double-buffering 2*n_l full-length caches per token —
            # mx.check `donation-miss`)
            run_flat = jit_flat_step(self, step, 2 * n_l,
                                     donate_state=2 * n_l)

            def run(tok, t, sk, sv):
                logits, state = run_flat(tok, t, sk + sv)
                return logits, state[:n_l], state[n_l:]

            self._gen_cache[key] = run
        return self._gen_cache[key], self_k, self_v

    def _prefill_body(self, prompt_d, lp_d, flat):
        """Raw-jax batched prefill: embed + per-layer flash pass writing
        K/V[0:Lp]; returns (f32 logits at the last REAL prompt position
        (B, V), ks, vs). Shared by the on-device generation program and
        the beam-search prefill program."""
        import jax
        import jax.numpy as jnp

        g = self.gpt
        Lp_b = prompt_d.shape[1]
        n_l = len(g.layers)
        x = g.word_embed(NDArray(prompt_d))
        x = x + NDArray(
            g.position_embed.data()._data[:Lp_b]).expand_dims(axis=0)
        ks, vs = list(flat[:n_l]), list(flat[n_l:])
        for i, layer in enumerate(g.layers):
            x, k, v = layer.prefill(x, NDArray(ks[i]), NDArray(vs[i]))
            ks[i], vs[i] = k._data, v._data
        h = g.ln_f(x)._data
        h_last = jax.lax.dynamic_index_in_dim(
            h, (lp_d - 1).astype(jnp.int32), axis=1, keepdims=False)
        w = g.word_embed.weight.data()._data
        logits = jnp.matmul(h_last, w.T.astype(h_last.dtype)) \
            .astype(jnp.float32)
        return logits, ks, vs

    def _init_prefill(self, B, Lp_b, max_len):
        """Jitted prefill-only program: ONE dispatch fills the caches and
        returns the first-expansion logits (beam search's prefill)."""
        n_l = len(self.gpt.layers)
        key = ("prefill", B, Lp_b, max_len)
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            from ._decode import jit_flat_step

            def pre(prompt_nd, lp_nd, flat):
                logits, ks, vs = self._prefill_body(
                    prompt_nd._data, lp_nd._data, [f._data for f in flat])
                return logits, ks + vs

            # the zeroed caches passed in alias straight into the filled
            # ones coming out (donated: no transient double allocation of
            # the full-length K/V at prefill)
            self._gen_cache[key] = jit_flat_step(self, pre, 2 * n_l,
                                                 donate_state=2 * n_l)
        return self._gen_cache[key]

    def _alloc_caches(self, B, max_len):
        """Zeroed per-layer K+V caches (the single source of cache
        geometry for both generation paths)."""
        import jax.numpy as jnp

        g = self.gpt
        n_l = len(g.layers)
        H = g.layers[0].attn._num_heads
        D = g.word_embed.weight.shape[1] // H
        dt = g.word_embed.weight.data()._data.dtype
        return [jnp.zeros((B, H, max_len, D), dt) for _ in range(2 * n_l)]

    def _generate_on_device(self, prompt, max_new, eos, temperature, top_k,
                            seed, max_len):
        """Whole-generation as ONE jitted program: a batched flash
        prefill fills the K/V caches, then a generation lax.scan samples
        inside the trace — one host<->device round trip total instead of
        one per token, which over a high-latency link (the axon tunnel)
        dominates generation wall time.

        The prompt right-pads to a bucket so one compile serves a range
        of prompt lengths; temperature/eos/seed are traced scalars so
        sweeping them reuses the compile (top_k and max_new are
        structural: static). Pad-slot cache pollution is harmless:
        prefill attention is causal (real positions never see pad slots)
        and each generated step overwrites its slot before attending."""
        import jax
        import jax.numpy as jnp

        B, Lp = prompt.shape
        Lp_b = 16
        while Lp_b < Lp:
            Lp_b *= 2
        Lp_b = min(Lp_b, max_len - 1)
        pad = np.zeros((B, Lp_b - Lp), np.int32)
        prompt_pad = np.concatenate([prompt, pad], axis=1)

        n_l = len(self.gpt.layers)
        do_sample = bool(temperature and temperature > 0.0)
        key = ("dev", B, Lp_b, max_new, max_len, do_sample, int(top_k),
               eos is not None)
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            from ._decode import jit_flat_step
            model = self

            def whole(prompt_d, lp_d, seed_d, temp_d, eos_d, flat):
                # jit_flat_step hands us NDArray-wrapped tracers; this
                # body speaks raw jax (lax.scan carries), so unwrap here
                prompt_d, lp_d, seed_d, temp_d, eos_d = (
                    prompt_d._data, lp_d._data, seed_d._data, temp_d._data,
                    eos_d._data)
                flat = [f._data for f in flat]

                def wrap(d):
                    return NDArray(d)

                logits, ks, vs = model._prefill_body(prompt_d, lp_d, flat)

                rngk = jax.random.fold_in(
                    jax.random.key(0), seed_d.astype(jnp.int32))

                def gen_t(carry, i):
                    logits, ks, vs, finished, rngk = carry
                    lg = logits
                    if do_sample:
                        if top_k:
                            kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
                            lg = jnp.where(lg < kth, -jnp.inf, lg)
                        rngk, sub = jax.random.split(rngk)
                        nxt = jax.random.categorical(
                            sub, lg / temp_d, axis=-1).astype(jnp.int32)
                    else:
                        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    if eos is not None:
                        nxt = jnp.where(finished, eos_d.astype(jnp.int32),
                                        nxt)
                        finished = finished | (nxt == eos_d)
                    t = lp_d + i
                    lg2, nk, nv = model.decode_step(
                        wrap(nxt), wrap(t),
                        [wrap(k) for k in ks], [wrap(v) for v in vs])
                    return (lg2._data.astype(jnp.float32),
                            tuple(k._data for k in nk),
                            tuple(v._data for v in nv),
                            finished, rngk), nxt

                finished0 = jnp.zeros((B,), bool)
                (_, _, _, _, _), toks = jax.lax.scan(
                    gen_t, (logits, tuple(ks), tuple(vs), finished0, rngk),
                    jnp.arange(max_new))
                return toks.T, []       # (B, max_new)

            run = jit_flat_step(self, whole, 2 * n_l)
            self._gen_cache[key] = run
        run = self._gen_cache[key]
        toks, _ = run(jnp.asarray(prompt_pad), jnp.asarray(Lp, jnp.int32),
                      jnp.asarray(seed, jnp.int32),
                      jnp.asarray(float(temperature or 1.0), jnp.float32),
                      jnp.asarray(-1 if eos is None else eos, jnp.int32),
                      self._alloc_caches(B, max_len))
        out = np.asarray(toks, np.int32)
        if eos is not None:
            # trim trailing columns after every row finished (host-loop
            # semantics: the step where the last row emits eos is kept)
            allf = np.all(np.cumsum(out == eos, axis=1) >= 1, axis=0)
            if allf.any():
                out = out[:, :int(np.argmax(allf)) + 1]
        return out

    def _generate_beam(self, prompt, max_new, eos, num_beams, alpha,
                       max_len, return_scores):
        """Beam search over the KV cache (the gluonnlp BeamSearchSampler
        surface): device steps + host top-k bookkeeping + on-device cache
        reorder gathers — the same shared driver as TransformerNMT."""
        import jax.numpy as jnp

        from ._decode import beam_search_loop

        B, Lp = prompt.shape
        # ONE batched-prefill dispatch at batch B (beams are identical
        # copies until the first expansion), then tile the caches: row
        # b*beam+j is beam j of batch b — exactly the layout reorder's
        # gather indices expect. Prompt right-pads to a bucket (pad-slot
        # pollution is harmless — see _generate_on_device).
        Lp_b = 16
        while Lp_b < Lp:
            Lp_b *= 2
        Lp_b = min(Lp_b, max_len - 1)
        prompt_pad = np.concatenate(
            [prompt, np.zeros((B, Lp_b - Lp), np.int32)], axis=1)
        pre = self._init_prefill(B, Lp_b, max_len)
        n_l = len(self.gpt.layers)
        logits0, caches = pre(jnp.asarray(prompt_pad),
                              jnp.asarray(Lp, jnp.int32),
                              self._alloc_caches(B, max_len))
        pk, pv = caches[:n_l], caches[n_l:]
        run, _, _ = self._init_generate(B * num_beams, max_len)
        state = {"k": [jnp.repeat(c, num_beams, axis=0) for c in pk],
                 "v": [jnp.repeat(c, num_beams, axis=0) for c in pv]}
        logits0 = jnp.repeat(jnp.asarray(logits0), num_beams, axis=0)

        def dev_step(tok, t):
            logits, state["k"], state["v"] = run(
                jnp.asarray(tok), jnp.asarray(t, jnp.int32),
                state["k"], state["v"])
            return logits

        def reorder(gather):
            g = jnp.asarray(gather)
            state["k"] = [jnp.take(c, g, axis=0) for c in state["k"]]
            state["v"] = [jnp.take(c, g, axis=0) for c in state["v"]]

        out, scores = beam_search_loop(
            logits0, lambda tok, i: dev_step(tok, Lp + i), reorder,
            B, num_beams, eos, max_new, alpha=alpha)
        return (out, scores) if return_scores else out

    def generate(self, prompt, max_new_tokens=32, eos=None, temperature=0.0,
                 top_k=0, seed=0, on_device=True, num_beams=1, alpha=0.6,
                 return_scores=False):
        """Autoregressive generation from int prompt tokens (B, Lp):
        greedy when temperature == 0, else softmax sampling at the given
        temperature (optionally truncated to the top_k logits) — the
        gluonnlp text_generation sampler surface. Returns (B, <=
        max_new_tokens) numpy tokens (rows stop growing at `eos`).

        on_device=True (default) runs prefill + the whole generation loop
        as one jitted program (lax.scan, sampling in-trace) — a single
        dispatch instead of one per token. on_device=False single-steps
        through the same jitted one-token step from the host (useful for
        debugging; identical greedy results, different sample streams).

        num_beams > 1 switches to beam search (requires `eos`; Sockeye
        length norm with `alpha`; `return_scores` adds per-batch scores).
        """
        import jax.numpy as jnp

        prompt = np.asarray(prompt, np.int32)
        B, Lp = prompt.shape
        need = Lp + max_new_tokens
        limit = self.gpt.position_embed.shape[0]
        if need > limit:
            raise ValueError(
                f"prompt {Lp} + max_new_tokens {max_new_tokens} exceeds "
                f"max_length {limit}")
        if Lp == 0 or max_new_tokens <= 0:
            return np.zeros((B, 0), np.int32)
        # bucket the cache length (next power of two, capped at the
        # position table) so one compile serves every prompt length —
        # t is traced, only the cache SHAPE keys the jit
        max_len = 16
        while max_len < need:
            max_len *= 2
        max_len = min(max_len, limit)
        if num_beams > 1:
            if eos is None:
                raise ValueError("beam search needs an `eos` id (scoring "
                                 "terminates beams on it)")
            if (temperature and temperature > 0.0) or top_k:
                raise ValueError("num_beams > 1 is deterministic beam "
                                 "search — temperature/top_k do not apply")
            return self._generate_beam(prompt, max_new_tokens, eos,
                                       num_beams, alpha, max_len,
                                       return_scores)
        if on_device:
            return self._generate_on_device(
                prompt, max_new_tokens, eos, temperature, top_k, seed,
                max_len)
        run, self_k, self_v = self._init_generate(B, max_len)
        rng = np.random.RandomState(seed)
        logits = None
        for t in range(Lp):
            logits, self_k, self_v = run(
                jnp.asarray(prompt[:, t]), jnp.asarray(t, jnp.int32),
                self_k, self_v)
        out = []
        finished = np.zeros(B, bool)
        for i in range(max_new_tokens):
            lg = np.asarray(logits, np.float32)
            if temperature and temperature > 0.0:
                if top_k:
                    kth = np.partition(lg, -top_k, axis=-1)[:, -top_k][:, None]
                    lg = np.where(lg < kth, -np.inf, lg)
                lg = lg / temperature
                p = np.exp(lg - lg.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                nxt = np.stack([rng.choice(p.shape[1], p=p[b])
                                for b in range(B)]).astype(np.int32)
            else:
                nxt = lg.argmax(-1).astype(np.int32)
            if eos is not None:
                nxt = np.where(finished, eos, nxt)
                finished |= nxt == eos
            out.append(nxt)
            if eos is not None and finished.all():
                break
            if i < max_new_tokens - 1:
                logits, self_k, self_v = run(
                    jnp.asarray(nxt), jnp.asarray(Lp + i, jnp.int32),
                    self_k, self_v)
        return np.stack(out, axis=1)


def gpt_lm_loss(logits, labels, weights):
    """Next-token cross entropy on NDArrays (ShardedTrainer loss_fn and
    eager compatible). logits (B, L, V) at input positions, labels (B, L)
    the NEXT token at each position (pre-shifted by the data pipeline so
    sequence-parallel shards stay self-contained), weights (B, L) 0/1."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import apply_op

    def compute(lg, lb, w):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(
            logp, lb.astype(jnp.int32)[..., None], -1)[..., 0]
        w = w.astype(jnp.float32)
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)

    return apply_op(compute, logits, labels, weights)


def make_synthetic_batch(cfg, batch_size, seq_len, seed=0):
    """Tokens + pre-shifted next-token labels + weights, numpy."""
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, cfg["vocab_size"],
                       (batch_size, seq_len + 1)).astype(np.int32)
    return {
        "input_ids": toks[:, :-1],
        "labels": toks[:, 1:],
        "weights": np.ones((batch_size, seq_len), np.float32),
        "valid_length": np.full((batch_size,), seq_len, np.int32),
    }


def tp_rules(tp_axis="tp"):
    """Megatron sharding for GPT params: bert.tp_rules verbatim (the block
    param names match by construction) plus the position table on its
    feature dim — the tied LM head then contracts over the sharded dim
    with a psum."""
    from jax.sharding import PartitionSpec as P
    return _bert_tp_rules(tp_axis) + [(r"position_weight$", P(None, tp_axis))]
