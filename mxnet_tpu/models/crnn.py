"""CRNN sequence recognition: conv features -> BiLSTM -> CTC (the classic
OCR stack).

Reference: the upstream `example/ctc/` family (lstm_ocr.py + warp-ctc) and
the CRNN architecture it popularized. TPU-first: the conv stack and the
fused-scan BiLSTM compile into one XLA program with the CTC alpha
recursion (ops.misc_ops.ctc_loss), so a full train step is a single
dispatch; variable-width inputs ride the RNN op's use_sequence_length
mode rather than host-side bucketing.
"""
from __future__ import annotations

import numpy as np

from ..gluon import HybridBlock, nn, rnn
from ..ndarray import ndarray as F


class CRNN(HybridBlock):
    """(N, 1, H, W) image -> (T=W/2, N, num_classes) CTC logits.

    num_classes INCLUDES the blank at index 0 (blank_label='first');
    real glyph classes are 1..num_classes-1.
    """

    def __init__(self, num_classes, img_height=8, channels=(16, 32),
                 hidden=64, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.conv = nn.HybridSequential()
        for i, c in enumerate(channels):
            self.conv.add(nn.Conv2D(c, kernel_size=3, padding=1,
                                    in_channels=1 if i == 0
                                    else channels[i - 1]))
            self.conv.add(nn.Activation("relu"))
            # halve H each stage; halve W only in the LAST stage so the
            # sequence keeps >= one frame per glyph column
            self.conv.add(nn.MaxPool2D(pool_size=2, strides=(2, 2)
                                       if i == len(channels) - 1
                                       else (2, 1)))
        feat_h = img_height // (2 ** len(channels))
        self.lstm = rnn.LSTM(hidden, num_layers=1, bidirectional=True,
                             input_size=channels[-1] * feat_h)
        self.head = nn.Dense(num_classes, flatten=False,
                             in_units=2 * hidden)

    def forward(self, x):
        f = self.conv(x)                       # (N, C, H', T)
        N, C, H, T = f.shape
        f = f.reshape((N, C * H, T))
        f = F.transpose(f, axes=(2, 0, 1))     # (T, N, C*H')
        h = self.lstm(f)                       # (T, N, 2*hidden)
        return self.head(h)                    # (T, N, num_classes)


def ctc_greedy_decode(logits, blank=0):
    """(T, N, C) logits -> list of N label lists: argmax path, collapse
    repeats, drop blanks (reference: the decode loop in
    example/ctc/lstm_ocr.py)."""
    path = np.asarray(logits).argmax(-1)       # (T, N)
    out = []
    for n in range(path.shape[1]):
        seq, prev = [], blank
        for t in path[:, n]:
            if t != prev and t != blank:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def make_glyph_batch(batch, num_glyphs=5, min_len=2, max_len=4,
                     img_height=8, glyph_w=6, noise=0.15, seed=0):
    """Synthetic rendered-string task with a knowable optimum: each glyph
    class g (1..num_glyphs) renders as a deterministic img_height x
    glyph_w binary pattern (seeded); a string of glyphs is drawn at
    random horizontal offsets with pixel noise. 100% sequence accuracy is
    attainable, so a falsifiable gate can sit on top (the
    SyntheticGratings pattern).

    Returns dict(image (N,1,H,W) f32, label (N,max_len) int32 0-padded,
    label_len (N,) int32)."""
    rs = np.random.RandomState(seed)
    glyphs = (np.random.RandomState(1234)
              .rand(num_glyphs + 1, img_height, glyph_w) > 0.5)
    W = max_len * (glyph_w + 2) + 4
    imgs = np.zeros((batch, 1, img_height, W), np.float32)
    labels = np.zeros((batch, max_len), np.int32)
    lens = rs.randint(min_len, max_len + 1, batch).astype(np.int32)
    for n in range(batch):
        x = rs.randint(0, 3)
        for i in range(lens[n]):
            g = rs.randint(1, num_glyphs + 1)
            labels[n, i] = g
            imgs[n, 0, :, x:x + glyph_w] = glyphs[g]
            x += glyph_w + rs.randint(1, 3)
    imgs += noise * rs.randn(*imgs.shape).astype(np.float32)
    return {"image": imgs, "label": labels, "label_len": lens}
