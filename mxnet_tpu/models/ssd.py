"""SSD detection (BASELINE.json workload #4: SSD300 / YOLOv3 family).

Reference: GluonCV SSD (VGG/ResNet backbone + multi-scale heads + anchors +
MultiBoxTarget/NMS ops from `src/operator/contrib/`). TPU-first choices:
anchors are precomputed host-side constants; matching and hard-negative
mining are vectorized jnp (static shapes); NMS is an O(N²) mask-matrix
suppression inside jit (XLA-friendly) instead of the reference's sequential
CUDA kernel.
"""
from __future__ import annotations

import itertools
import math

import numpy as np

from ..gluon import nn, HybridBlock
from ..ndarray import NDArray
from ..ndarray import ndarray as F

__all__ = ["SSD", "generate_anchors", "multibox_target", "non_max_suppression",
           "MultiBoxLoss"]


# --------------------------------------------------------------------------
# anchors (reference: `src/operator/contrib/multibox_prior.cc`)
# --------------------------------------------------------------------------

def generate_anchors(feat_sizes, image_size=300,
                     sizes=((0.1, 0.141), (0.2, 0.272), (0.37, 0.447),
                            (0.54, 0.619), (0.71, 0.79), (0.88, 0.961)),
                     ratios=((1, 2, 0.5),) * 6):
    """Returns (N, 4) center-size anchors in [0,1] coords."""
    anchors = []
    for (fh, fw), size, ratio in zip(feat_sizes, sizes, ratios):
        for i, j in itertools.product(range(fh), range(fw)):
            cy, cx = (i + 0.5) / fh, (j + 0.5) / fw
            s0, s1 = size[0], size[1]
            anchors.append([cx, cy, s0, s0])
            anchors.append([cx, cy, math.sqrt(s0 * s1), math.sqrt(s0 * s1)])
            for r in ratio:
                if r == 1:
                    continue
                sr = math.sqrt(r)
                anchors.append([cx, cy, s0 * sr, s0 / sr])
    return np.asarray(anchors, np.float32)


def _corner(boxes):
    import jax.numpy as jnp
    cx, cy, w, h = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)


def _iou(a, b):
    """a (N,4), b (M,4) corner boxes → (N,M)."""
    import jax.numpy as jnp
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-12)


def multibox_target(anchors, gt_boxes, gt_labels, iou_thresh=0.5):
    """Match anchors to ground truth (reference: MultiBoxTarget).

    anchors (N,4) center-size; gt_boxes (B,M,4) corner, padded with -1;
    gt_labels (B,M) padded with -1. Returns cls_targets (B,N) [0=bg],
    box_targets (B,N,4), box_mask (B,N,1).
    """
    import jax.numpy as jnp
    anchors_c = _corner(anchors)

    def one(gtb, gtl):
        valid = gtl >= 0
        iou = _iou(anchors_c, gtb)                     # (N, M)
        iou = jnp.where(valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)              # (N,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= iou_thresh
        # force-match: each gt's best anchor
        best_anchor = jnp.argmax(iou, axis=0)          # (M,)
        forced = jnp.zeros(anchors.shape[0], bool).at[best_anchor].set(valid)
        matched = matched | forced
        gt_for_anchor = gtb[best_gt]                   # (N,4) corner
        lbl = jnp.where(matched, gtl[best_gt] + 1, 0)  # 0 = background
        # encode (reference MultiBoxTarget variances 0.1/0.2)
        gw = gt_for_anchor[:, 2] - gt_for_anchor[:, 0]
        gh = gt_for_anchor[:, 3] - gt_for_anchor[:, 1]
        gx = (gt_for_anchor[:, 0] + gt_for_anchor[:, 2]) / 2
        gy = (gt_for_anchor[:, 1] + gt_for_anchor[:, 3]) / 2
        tx = (gx - anchors[:, 0]) / anchors[:, 2] / 0.1
        ty = (gy - anchors[:, 1]) / anchors[:, 3] / 0.1
        tw = jnp.log(jnp.maximum(gw, 1e-6) / anchors[:, 2]) / 0.2
        th = jnp.log(jnp.maximum(gh, 1e-6) / anchors[:, 3]) / 0.2
        box_t = jnp.stack([tx, ty, tw, th], -1) * matched[:, None]
        return lbl, box_t, matched[:, None].astype(jnp.float32)

    import jax
    return jax.vmap(one)(gt_boxes, gt_labels)


def non_max_suppression(boxes, scores, iou_thresh=0.45, topk=100):
    """XLA-friendly NMS: O(N²) suppression matrix + top-k, static shapes.

    boxes (N,4) corner, scores (N,). Returns (topk indices, topk scores);
    suppressed entries get score -1.
    """
    import jax.numpy as jnp
    N = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    iou = _iou(b, b)
    keep_mask = jnp.ones(N, bool)

    def body(i, keep):
        sup = (iou[i] > iou_thresh) & keep[i] & (jnp.arange(N) > i)
        return keep & ~sup

    import jax
    keep_mask = jax.lax.fori_loop(0, min(N, topk), body, keep_mask)
    s = jnp.where(keep_mask, s, -1.0)
    k = min(topk, N)
    top_s, top_i = jax.lax.top_k(s, k)
    return order[top_i], top_s


class SSD(HybridBlock):
    """SSD with a ResNet-ish backbone and multi-scale heads."""

    def __init__(self, num_classes=20, num_anchors_per_pos=4, channels=(64, 128, 256, 512),
                 **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self._na = num_anchors_per_pos
        self.stem = nn.HybridSequential()
        self.stem.add(nn.Conv2D(channels[0], 3, 2, 1, activation="relu"),
                      nn.BatchNorm())
        self.stages = nn.HybridSequential()
        self.cls_heads = nn.HybridSequential()
        self.box_heads = nn.HybridSequential()
        for c in channels:
            stage = nn.HybridSequential()
            stage.add(nn.Conv2D(c, 3, 2, 1, use_bias=False), nn.BatchNorm(),
                      nn.Activation("relu"),
                      nn.Conv2D(c, 3, 1, 1, use_bias=False), nn.BatchNorm(),
                      nn.Activation("relu"))
            self.stages.add(stage)
            self.cls_heads.add(nn.Conv2D(self._na * (num_classes + 1), 3, 1, 1))
            self.box_heads.add(nn.Conv2D(self._na * 4, 3, 1, 1))

    def forward(self, x):
        """Returns (cls_preds (B,N,C+1), box_preds (B,N,4), feat_sizes)."""
        x = self.stem(x)
        cls_out, box_out, feat_sizes = [], [], []
        for stage, ch, bh in zip(self.stages, self.cls_heads, self.box_heads):
            x = stage(x)
            feat_sizes.append(x.shape[2:])
            B = x.shape[0]
            c = ch(x).transpose(axes=(0, 2, 3, 1)) \
                .reshape(shape=(B, -1, self.num_classes + 1))
            b = bh(x).transpose(axes=(0, 2, 3, 1)).reshape(shape=(B, -1, 4))
            cls_out.append(c)
            box_out.append(b)
        return (F.concat(*cls_out, dim=1), F.concat(*box_out, dim=1), feat_sizes)


class MultiBoxLoss:
    """SSD loss: softmax CE (with hard negative mining 3:1) + smooth-L1."""

    def __init__(self, neg_ratio=3.0):
        self.neg_ratio = neg_ratio

    def __call__(self, cls_preds, box_preds, cls_targets, box_targets, box_mask):
        import jax
        import jax.numpy as jnp
        from ..ndarray import apply_op

        def compute(cp, bp, ct, bt, bm):
            logp = jax.nn.log_softmax(cp.astype(jnp.float32), -1)
            ct = ct.astype(jnp.int32)
            nll = -jnp.take_along_axis(logp, ct[..., None], -1)[..., 0]  # (B,N)
            pos = ct > 0
            n_pos = jnp.maximum(jnp.sum(pos, 1), 1)
            # hard negative mining: top (neg_ratio * n_pos) negatives by loss
            neg_loss = jnp.where(pos, -jnp.inf, nll)
            rank = jnp.argsort(jnp.argsort(-neg_loss, 1), 1)
            neg = rank < (self.neg_ratio * n_pos)[:, None]
            cls_loss = jnp.sum(nll * (pos | neg), 1) / n_pos
            diff = jnp.abs(bp.astype(jnp.float32) - bt.astype(jnp.float32)) * bm
            sl1 = jnp.where(diff < 1, 0.5 * diff * diff, diff - 0.5)
            box_loss = jnp.sum(sl1, (1, 2)) / n_pos
            return jnp.mean(cls_loss + box_loss)

        return apply_op(compute, cls_preds, box_preds, cls_targets,
                        box_targets, box_mask)
