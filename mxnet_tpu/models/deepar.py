"""DeepAR probabilistic forecasting (BASELINE.json workload #5).

Reference: GluonTS DeepAREstimator (autoregressive LSTM emitting distribution
parameters; trained by negative log-likelihood, forecast by ancestral
sampling). TPU-first: the LSTM is the lax.scan fused layer; sampling rolls
the network with a scan as well, so the whole sampler jits.
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn, rnn, HybridBlock
from ..ndarray import NDArray
from ..ndarray import ndarray as F


class GaussianOutput:
    """Distribution head: projects hidden → (mu, sigma)."""

    args_dim = 2

    @staticmethod
    def params(raw):
        import jax.numpy as jnp
        mu = raw[..., 0]
        sigma = jnp.logaddexp(raw[..., 1], 0.0) + 1e-6  # softplus
        return mu, sigma

    @staticmethod
    def nll(raw, target):
        import jax.numpy as jnp
        mu, sigma = GaussianOutput.params(raw)
        t = target.astype(jnp.float32)
        return 0.5 * jnp.log(2 * jnp.pi) + jnp.log(sigma) + \
            0.5 * jnp.square((t - mu) / sigma)

    @staticmethod
    def sample(raw, key):
        import jax
        import jax.numpy as jnp
        mu, sigma = GaussianOutput.params(raw)
        return mu + sigma * jax.random.normal(key, mu.shape)


class NegativeBinomialOutput:
    args_dim = 2

    @staticmethod
    def params(raw):
        import jax.numpy as jnp
        mu = jnp.logaddexp(raw[..., 0], 0.0) + 1e-6
        alpha = jnp.logaddexp(raw[..., 1], 0.0) + 1e-6
        return mu, alpha

    @staticmethod
    def nll(raw, target):
        import jax.numpy as jnp
        from jax.scipy.special import gammaln
        mu, alpha = NegativeBinomialOutput.params(raw)
        t = target.astype(jnp.float32)
        r = 1.0 / alpha
        p = mu / (mu + r)
        return -(gammaln(t + r) - gammaln(r) - gammaln(t + 1)
                 + r * jnp.log(1 - p) + t * jnp.log(p))

    @staticmethod
    def sample(raw, key):
        import jax
        import jax.numpy as jnp
        mu, alpha = NegativeBinomialOutput.params(raw)
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        rate = jax.random.gamma(k1, r) * mu * alpha
        return jax.random.poisson(k2, rate).astype(jnp.float32)


class DeepAR(HybridBlock):
    """context window conditioning → h; prediction by NLL on known targets
    (training) or ancestral sampling (forecast)."""

    def __init__(self, num_cells=40, num_layers=2, context_length=24,
                 prediction_length=12, distr=GaussianOutput, num_features=1,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.context_length = context_length
        self.prediction_length = prediction_length
        self.distr = distr
        self.lstm = rnn.LSTM(num_cells, num_layers=num_layers, layout="NTC",
                             dropout=dropout, input_size=num_features + 1)
        self.proj = nn.Dense(distr.args_dim, in_units=num_cells, flatten=False)

    def forward(self, past_target, features=None):
        """Teacher-forced: past_target (B, T); returns raw distr params
        (B, T-1, args_dim) predicting target[t] from target[<t]."""
        import jax.numpy as jnp
        from ..ndarray import apply_op

        def make_input(t, f=None):
            x = t[:, :-1, None].astype(jnp.float32)  # lagged input
            extra = f[:, :-1].astype(jnp.float32) if f is not None \
                else jnp.zeros_like(x)
            return jnp.concatenate([x, extra], axis=-1)

        x = apply_op(make_input, past_target) if features is None \
            else apply_op(make_input, past_target, features)
        h = self.lstm(x)
        return self.proj(h)

    def loss(self, past_target, features=None):
        raw = self.forward(past_target, features)
        import jax.numpy as jnp
        from ..ndarray import apply_op
        return apply_op(
            lambda r, t: jnp.mean(self.distr.nll(r, t[:, 1:])),
            raw, past_target)

    def sample_paths(self, context, num_samples=100, features=None):
        """Ancestral sampling: returns (num_samples, B, prediction_length).

        TPU-shaped: the `num_samples` axis folds into the batch (one LSTM
        pass over the tiled context), then each forecast step advances the
        recurrent state INCREMENTALLY — no per-sample python loop, no
        re-running the growing prefix.  Alignment note: `forward` drops
        the final input (teacher-forcing: raw[:, k] conditions on
        target[<=k], scored against target[k+1]), so conditioning for the
        first forecast step must come from the FULL context — an earlier
        version sampled from forward()'s raw[:, -1], which predicts the
        last OBSERVED point and lagged every path by one step (caught by
        the climatology CRPS gate in test_quality_gates)."""
        import jax.numpy as jnp
        from .. import random as _random
        from ..ndarray import zeros as nd_zeros

        if features is not None:
            raise NotImplementedError(
                "sample_paths with covariate features: forecasting would "
                "need future feature values threaded per sampled step; "
                "train/forecast feature-free or extend sample_paths")
        B, T0 = context.shape
        S = num_samples
        ctx = jnp.tile(context._data.astype(jnp.float32), (S, 1))  # (S*B,T0)
        x = ctx[:, :, None]
        x = NDArray(jnp.concatenate([x, jnp.zeros_like(x)], axis=-1))
        states = self.lstm.begin_state(S * B, func=nd_zeros)
        out, states = self.lstm(x, states)          # warm state on context
        raw_next = self.proj(NDArray(out._data[:, -1]))._data
        vals = []
        for t in range(self.prediction_length):
            val = self.distr.sample(raw_next, _random.next_key())  # (S*B,)
            vals.append(val)
            xt = val[:, None, None].astype(jnp.float32)
            xt = NDArray(jnp.concatenate([xt, jnp.zeros_like(xt)], axis=-1))
            out, states = self.lstm(xt, states)
            raw_next = self.proj(NDArray(out._data[:, -1]))._data
        return NDArray(jnp.stack(vals, axis=-1).reshape(
            S, B, self.prediction_length))


def crps_eval(samples, target):
    """Sample-based CRPS (GluonTS quality metric), numpy."""
    s = np.asarray(samples)  # (S, B, T)
    t = np.asarray(target)   # (B, T)
    term1 = np.mean(np.abs(s - t[None]), axis=0)
    term2 = 0.5 * np.mean(
        np.abs(s[:, None] - s[None, :]), axis=(0, 1))
    return float(np.mean(term1 - term2))
