"""DeepAR probabilistic forecasting (BASELINE.json workload #5).

Reference: GluonTS DeepAREstimator (autoregressive LSTM emitting distribution
parameters; trained by negative log-likelihood, forecast by ancestral
sampling). TPU-first: the LSTM is the lax.scan fused layer; sampling rolls
the network with a scan as well, so the whole sampler jits.
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn, rnn, HybridBlock
from ..ndarray import NDArray
from ..ndarray import ndarray as F


class GaussianOutput:
    """Distribution head: projects hidden → (mu, sigma)."""

    args_dim = 2

    @staticmethod
    def params(raw):
        import jax.numpy as jnp
        mu = raw[..., 0]
        sigma = jnp.logaddexp(raw[..., 1], 0.0) + 1e-6  # softplus
        return mu, sigma

    @staticmethod
    def nll(raw, target):
        import jax.numpy as jnp
        mu, sigma = GaussianOutput.params(raw)
        t = target.astype(jnp.float32)
        return 0.5 * jnp.log(2 * jnp.pi) + jnp.log(sigma) + \
            0.5 * jnp.square((t - mu) / sigma)

    @staticmethod
    def sample(raw, key):
        import jax
        import jax.numpy as jnp
        mu, sigma = GaussianOutput.params(raw)
        return mu + sigma * jax.random.normal(key, mu.shape)


class NegativeBinomialOutput:
    args_dim = 2

    @staticmethod
    def params(raw):
        import jax.numpy as jnp
        mu = jnp.logaddexp(raw[..., 0], 0.0) + 1e-6
        alpha = jnp.logaddexp(raw[..., 1], 0.0) + 1e-6
        return mu, alpha

    @staticmethod
    def nll(raw, target):
        import jax.numpy as jnp
        from jax.scipy.special import gammaln
        mu, alpha = NegativeBinomialOutput.params(raw)
        t = target.astype(jnp.float32)
        r = 1.0 / alpha
        p = mu / (mu + r)
        return -(gammaln(t + r) - gammaln(r) - gammaln(t + 1)
                 + r * jnp.log(1 - p) + t * jnp.log(p))

    @staticmethod
    def sample(raw, key):
        import jax
        import jax.numpy as jnp
        mu, alpha = NegativeBinomialOutput.params(raw)
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        rate = jax.random.gamma(k1, r) * mu * alpha
        return jax.random.poisson(k2, rate).astype(jnp.float32)


class DeepAR(HybridBlock):
    """context window conditioning → h; prediction by NLL on known targets
    (training) or ancestral sampling (forecast)."""

    def __init__(self, num_cells=40, num_layers=2, context_length=24,
                 prediction_length=12, distr=GaussianOutput, num_features=1,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.context_length = context_length
        self.prediction_length = prediction_length
        self.distr = distr
        self.lstm = rnn.LSTM(num_cells, num_layers=num_layers, layout="NTC",
                             dropout=dropout, input_size=num_features + 1)
        self.proj = nn.Dense(distr.args_dim, in_units=num_cells, flatten=False)

    def forward(self, past_target, features=None):
        """Teacher-forced: past_target (B, T); returns raw distr params
        (B, T-1, args_dim) predicting target[t] from target[<t]."""
        import jax.numpy as jnp
        from ..ndarray import apply_op

        def make_input(t, f=None):
            x = t[:, :-1, None].astype(jnp.float32)  # lagged input
            extra = f[:, :-1].astype(jnp.float32) if f is not None \
                else jnp.zeros_like(x)
            return jnp.concatenate([x, extra], axis=-1)

        x = apply_op(make_input, past_target) if features is None \
            else apply_op(make_input, past_target, features)
        h = self.lstm(x)
        return self.proj(h)

    def loss(self, past_target, features=None):
        raw = self.forward(past_target, features)
        import jax.numpy as jnp
        from ..ndarray import apply_op
        return apply_op(
            lambda r, t: jnp.mean(self.distr.nll(r, t[:, 1:])),
            raw, past_target)

    def _next_step_raw(self, seq):
        """Distr params for the step AFTER the last element of `seq`.

        `forward` drops the final input (teacher-forcing alignment:
        raw[:, k] is conditioned on target[<=k] and scored against
        target[k+1]), so its raw[:, -1] predicts the last OBSERVED point —
        sampling from that lags every forecast by one step (caught by the
        climatology CRPS gate in test_quality_gates)."""
        import jax.numpy as jnp

        x = seq[:, :, None].astype(jnp.float32)
        x = jnp.concatenate([x, jnp.zeros_like(x)], axis=-1)
        h = self.lstm(NDArray(x))
        return self.proj(h)._data[:, -1]

    def sample_paths(self, context, num_samples=100, features=None):
        """Ancestral sampling: returns (num_samples, B, prediction_length)."""
        import jax
        import jax.numpy as jnp
        from .. import random as _random

        if features is not None:
            raise NotImplementedError(
                "sample_paths with covariate features: forecasting would "
                "need future feature values threaded per sampled step; "
                "train/forecast feature-free or extend _next_step_raw")
        B = context.shape[0]
        out = []
        for s in range(num_samples):
            seq = context._data.astype(jnp.float32)
            for t in range(self.prediction_length):
                step_raw = self._next_step_raw(seq)
                val = self.distr.sample(step_raw, _random.next_key())
                seq = jnp.concatenate([seq, val[:, None]], axis=1)
            out.append(seq[:, context.shape[1]:])
        return NDArray(jnp.stack(out))


def crps_eval(samples, target):
    """Sample-based CRPS (GluonTS quality metric), numpy."""
    s = np.asarray(samples)  # (S, B, T)
    t = np.asarray(target)   # (B, T)
    term1 = np.mean(np.abs(s - t[None]), axis=0)
    term2 = 0.5 * np.mean(
        np.abs(s[:, None] - s[None, :]), axis=(0, 1))
    return float(np.mean(term1 - term2))
