"""Model zoo: the five BASELINE.json workload families (SURVEY.md §2.3/L12).

Reference ecosystems (GluonCV/GluonNLP/Sockeye/GluonTS) are separate repos
consuming only the Python API; here the models ship in-tree, built from
gluon blocks with TPU-first internals (flash attention, scan RNN, bf16).
"""
from . import bert
from . import resnet
from . import transformer
from . import deepar
from . import ssd
from . import yolo
from . import gpt

from .bert import BERTModel, BERTForPretraining, bert_base_config, bert_large_config
from .gpt import GPTModel, GPTForCausalLM, gpt2_117m_config, gpt2_345m_config
from .resnet import (get_resnet, resnet18_v1, resnet34_v1, resnet50_v1,
                     resnet101_v1, resnet152_v1, resnet18_v2, resnet34_v2,
                     resnet50_v2, resnet101_v2, resnet152_v2)
from .yolo import YOLOv3Tiny

__all__ = ["bert", "resnet", "transformer", "deepar", "ssd", "yolo", "gpt",
           "BERTModel", "BERTForPretraining", "bert_base_config",
           "bert_large_config", "GPTModel", "GPTForCausalLM",
           "gpt2_117m_config", "gpt2_345m_config",
           "get_resnet", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "YOLOv3Tiny"]
