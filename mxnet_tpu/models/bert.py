"""BERT — the flagship workload (BASELINE.json: GluonNLP BERT pretraining).

Reference: GluonNLP's BERTModel/BERTEncoder over mxnet's fused attention ops
(`src/operator/contrib/transformer.cc`). TPU-first re-design:
  * attention = Pallas flash kernel (mxnet_tpu.pallas_ops), bf16 in/f32 acc
  * one jitted train step via parallel.ShardedTrainer (LAMB, weight-update
    sharding); tp rules shard QKV/FFN Megatron-style; sp rules enable ring
    attention for long sequences
  * MLM gathers masked positions before the vocab projection so the big
    (B,P,V) logits tensor — not (B,L,V) — hits the MXU

Pretraining objective matches GluonNLP: MLM over masked positions + NSP.
"""
from __future__ import annotations

import math

import numpy as np

from ..gluon import nn, HybridBlock, loss as gloss
from ..gluon.parameter import Parameter
from ..ndarray import NDArray
from ..ndarray import ndarray as F


def bert_base_config(**overrides):
    cfg = dict(vocab_size=30522, units=768, hidden_size=3072, num_layers=12,
               num_heads=12, max_length=512, type_vocab_size=2, dropout=0.1,
               attn_dropout=None, seq_parallel=False, dtype="float32",
               remat=False, scan_layers=False)
    cfg.update(overrides)
    return cfg


def bert_large_config(**overrides):
    # remat by default at large depth: recompute each encoder layer in the
    # backward pass (jax.checkpoint) so activation memory scales O(1) in
    # depth instead of O(num_layers) — the FLOPs-for-HBM trade that makes
    # BERT-large batch sizes fit (SURVEY §7.4 item 4).  scan_layers
    # compiles the layer body ONCE via lax.scan instead of unrolling 24
    # copies: >25 min cold compile down to ~BERT-base compile time.
    cfg = bert_base_config(units=1024, hidden_size=4096, num_layers=24,
                           num_heads=16, remat=True, scan_layers=True)
    cfg.update(overrides)
    return cfg


def bert_long_config(**overrides):
    """Long-context pretraining config: sequence sharded over the mesh's
    `sp` axis (ring attention — SURVEY §5.7 north-star). Attention-
    probability dropout must be 0 under the ring (hidden dropout stays)."""
    cfg = bert_base_config(max_length=8192, seq_parallel=True,
                           attn_dropout=0.0, remat=True)
    cfg.update(overrides)
    return cfg


def bert_tiny_config(**overrides):
    """Test-scale config."""
    cfg = bert_base_config(vocab_size=128, units=64, hidden_size=128,
                           num_layers=2, num_heads=4, max_length=64, dropout=0.0)
    cfg.update(overrides)
    return cfg


class BERTAttention(HybridBlock):
    """Self-attention with fused QKV and the flash kernel (or ring attention
    over the `sp` mesh axis when seq_parallel is set). `causal=True` makes
    it the decoder-side block (GPT family) — same kernel, causal mask."""

    def __init__(self, units, num_heads, dropout=0.0, dtype="float32",
                 seq_parallel=False, causal=False, **kwargs):
        super().__init__(**kwargs)
        if seq_parallel and dropout > 0.0:
            raise ValueError(
                "attention-probability dropout is not supported under ring "
                "sequence parallelism; pass attn_dropout=0 in the config")
        self._units = units
        self._num_heads = num_heads
        self.qkv = nn.Dense(3 * units, in_units=units, flatten=False, dtype=dtype,
                            weight_initializer="xavier")
        self.proj = nn.Dense(units, in_units=units, flatten=False, dtype=dtype,
                             weight_initializer="xavier")
        self._dropout = dropout
        self._seq_parallel = seq_parallel
        self._causal = causal

    def forward(self, x, mask=None):
        # x: (B, L, E); mask: (B, L) 1=valid
        qkv = self.qkv(x)  # (B, L, 3E)
        out = F.fused_self_attention(qkv, mask, num_heads=self._num_heads,
                                     dropout=self._dropout,
                                     causal=self._causal,
                                     seq_parallel=self._seq_parallel)
        return self.proj(out)


class BERTEncoderLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 dtype="float32", attn_dropout=None, seq_parallel=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.attention = BERTAttention(
            units, num_heads,
            dropout if attn_dropout is None else attn_dropout, dtype,
            seq_parallel=seq_parallel)
        self.attn_ln = nn.LayerNorm(in_channels=units)
        self.ffn_in = nn.Dense(hidden_size, in_units=units, flatten=False,
                               dtype=dtype, weight_initializer="xavier")
        self.ffn_out = nn.Dense(units, in_units=hidden_size, flatten=False,
                                dtype=dtype, weight_initializer="xavier")
        self.ffn_ln = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        attn = self.attention(x, mask)
        if self.dropout:
            attn = self.dropout(attn)
        x = self.attn_ln(x + attn)
        h = F.Activation(self.ffn_in(x), act_type="gelu")
        h = self.ffn_out(h)
        if self.dropout:
            h = self.dropout(h)
        return self.ffn_ln(x + h)


def _remat_call(layer, x, mask, policy="layers"):
    """Apply one encoder layer under jax.checkpoint: the backward pass
    recomputes the layer's internals from its (x, mask) boundary instead of
    stashing every intermediate. Layer parameters ride in as closure
    constants (under functional_call they are the substituted tracers).
    `policy` picks WHAT survives inside the layer (mx.memsafe graduated
    remat): "layers"/"full" save nothing, "dots_saveable" keeps matmul
    outputs so only the cheap elementwise work recomputes."""
    import jax

    from .. import memsafe as _memsafe

    def f(xd, *md):
        out = layer(NDArray(xd), NDArray(md[0]) if md else None)
        return out._data

    args = (x._data,) + (() if mask is None else (mask._data,))
    return NDArray(
        jax.checkpoint(f, policy=_memsafe.jax_policy(policy))(*args))


def _full_remat_stack(layers, x, mask):
    """policy='full', unrolled path: per-layer checkpoints INSIDE one
    checkpoint around the whole stack — only the stack's (x, mask) inputs
    survive the forward pass; backward re-runs the stack (itself
    re-checkpointed per layer, so the recompute stays O(1) in depth)."""
    import jax

    def f(xd, *md):
        out = NDArray(xd)
        m = NDArray(md[0]) if md else None
        for layer in layers:
            out = _remat_call(layer, out, m, "full")
        return out._data

    args = (x._data,) + (() if mask is None else (mask._data,))
    return NDArray(jax.checkpoint(f)(*args))


def _stack_call(layers, x, mask, policy):
    """Apply an encoder stack unrolled, under one remat policy (mx.memsafe:
    "none" | "dots_saveable" | "layers" | "full")."""
    if policy == "full":
        return _full_remat_stack(layers, x, mask)
    for layer in layers:
        if policy != "none":
            x = _remat_call(layer, x, mask, policy)
        else:
            x = layer(x, mask)
    return x


def _scan_layers_call(layers, x, mask, policy):
    """Apply an identical-structure encoder stack as ONE `lax.scan` over
    stacked per-layer parameters: the layer body is traced and compiled
    once instead of `num_layers` times.  This is what makes BERT-large
    (24 layers) compile in roughly the time BERT-base does — the unrolled
    loop took >25 min cold over the axon tunnel (measured 2026-07-31).

    Mechanics: each layer's parameter tensors (identical pytree structure
    by construction) are stacked on a new leading axis *inside the trace*,
    so under `functional_call` the stack consumes the substituted per-layer
    tracers and gradients flow back to the individual parameters through
    the stack — the Block/Trainer/optimizer machinery is untouched.  The
    body runs layer 0's `forward` with its parameters swapped for the
    scanned slices (the same substitution trick `_make_pure_fn` uses).

    RNG: `next_key()` folds a PYTHON-side counter, which advances once at
    trace time — inside scan every iteration would replay identical
    dropout masks.  Each iteration therefore enters a fresh `key_scope`
    folding the layer index into one base key.

    `policy` (mx.memsafe graduated remat) wraps the body in
    `jax.checkpoint`: "layers" saves only the carry between iterations
    (activation memory O(1) in depth — the canonical scan-over-remat
    pairing), "dots_saveable" additionally keeps matmul outputs inside
    the body, and "full" puts one more checkpoint around the whole scan
    so only the stack inputs survive the forward pass."""
    import jax
    import jax.numpy as jnp

    from .. import memsafe as _memsafe
    from .. import random as _random

    if not isinstance(policy, str):
        # legacy use_remat boolean callers
        policy = "layers" if policy else "none"

    layer0 = layers[0]
    gp0, aux0 = layer0._param_lists()
    if aux0:
        raise ValueError("scan_layers requires encoder layers without "
                         "aux (grad_req='null') parameters")
    params0 = [p for _, p in gp0]
    per_layer = []
    for layer in layers:
        gp, aux = layer._param_lists()
        assert not aux and len(gp) == len(gp0)
        per_layer.append([p._data._data for _, p in gp])
    stacked = [jnp.stack(vals) for vals in zip(*per_layer)]
    base_key = _random.next_key()
    mask_d = None if mask is None else mask._data

    def body(carry, xs):
        idx, leaves = xs[0], xs[1:]
        saved = []
        for p, d in zip(params0, leaves):
            saved.append(p._data._data)
            p._data._data = d
        try:
            with _random.key_scope(jax.random.fold_in(base_key, idx)):
                out = layer0(NDArray(carry),
                             None if mask_d is None else NDArray(mask_d))
        finally:
            for p, d in zip(params0, saved):
                p._data._data = d
        return out._data, None

    if policy != "none":
        body = jax.checkpoint(body, policy=_memsafe.jax_policy(policy))

    def run_scan(x_d, *stk):
        xs = (jnp.arange(len(layers)),) + tuple(stk)
        y, _ = jax.lax.scan(body, x_d, xs)
        return y

    if policy == "full":
        run_scan = jax.checkpoint(run_scan)
    return NDArray(run_scan(x._data, *stacked))


def _positions(position_embed, L, sp_manual):
    """Slice L position embeddings. Inside a shard_map stage controlling
    `sp`, this device holds tokens [off, off+L) of the global sequence —
    slice ITS positions, not [0, L). The GLOBAL length is validated here:
    dynamic_slice clamps out-of-range starts, which would otherwise
    silently reuse shard 0's positions on every shard."""
    import jax
    max_len = position_embed.shape[0]
    if sp_manual:
        n = jax.lax.psum(1, "sp")       # static: axis size
        if L * n > max_len:
            raise ValueError(
                f"global sequence length {L * n} (local {L} x sp={n}) "
                f"exceeds max_length {max_len}")
        off = jax.lax.axis_index("sp") * L
        return NDArray(jax.lax.dynamic_slice_in_dim(
            position_embed.data()._data, off, L, 0))
    if L > max_len:
        raise ValueError(f"sequence length {L} exceeds max_length {max_len}")
    return NDArray(position_embed.data()._data[:L])


class BERTModel(HybridBlock):
    """Embeddings + encoder stack + pooler (reference: gluonnlp BERTModel)."""

    # remat policies route here (HybridBlock.remat / the remat_policy
    # knob): the encoder stack checkpoints per layer / per scan body
    # instead of wrapping the whole block (mx.memsafe graduated remat).
    # The legacy `remat=True` config flag stays the "layers" alias.
    _remat_handles_policy = True

    def __init__(self, vocab_size, units, hidden_size, num_layers, num_heads,
                 max_length=512, type_vocab_size=2, dropout=0.1,
                 attn_dropout=None, seq_parallel=False,
                 dtype="float32", remat=False, scan_layers=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._remat = remat
        self._scan_layers = scan_layers
        self._seq_parallel = seq_parallel
        self.word_embed = nn.Embedding(vocab_size, units, dtype=dtype,
                                       weight_initializer="xavier")
        self.token_type_embed = nn.Embedding(type_vocab_size, units, dtype=dtype,
                                             weight_initializer="xavier")
        self.position_embed = Parameter("position_weight", shape=(max_length, units),
                                        dtype=dtype, init="xavier")
        # sliced [:L] along dim 0 each step — keep that dim unsharded
        self.position_embed.shard_hint = "embedding"
        self.embed_ln = nn.LayerNorm(in_channels=units)
        self.embed_dropout = nn.Dropout(dropout) if dropout else None
        self.layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.layers.add(BERTEncoderLayer(units, hidden_size, num_heads,
                                             dropout, dtype,
                                             attn_dropout=attn_dropout,
                                             seq_parallel=seq_parallel))
        self.pooler = nn.Dense(units, in_units=units, flatten=False,
                               activation="tanh", dtype=dtype,
                               weight_initializer="xavier")

    def forward(self, inputs, token_types=None, valid_length=None):
        B, L = inputs.shape
        max_len = self.position_embed.shape[0]
        if L > max_len:
            raise ValueError(
                f"sequence length {L} exceeds max_length {max_len}")
        from ..parallel import in_manual
        sp_manual = self._seq_parallel and in_manual("sp")
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = x + _positions(self.position_embed, L, sp_manual).expand_dims(axis=0)
        x = self.embed_ln(x)
        if self.embed_dropout:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            import jax
            import jax.numpy as jnp
            vl = valid_length._data if isinstance(valid_length, NDArray) else valid_length
            idx = jnp.arange(L)
            if sp_manual:
                idx = idx + jax.lax.axis_index("sp") * L
            mask = NDArray(idx[None, :] < vl[:, None].astype(jnp.int32))
        if self._seq_parallel and not sp_manual:
            # anchor the sequence sharding early so GSPMD keeps (B, L, E)
            # activations sp-sharded between the attention shard_maps
            from ..ndarray import apply_op
            from ..parallel import specs as _sp
            x = apply_op(_sp.constrain_seq, x)
        from .. import _engine
        from .. import memsafe as _memsafe
        # remat only where it means something: inside a jit trace (the
        # eager tape stores activations per-op; jax.checkpoint there would
        # just break recording)
        policy = _memsafe.effective_policy(
            getattr(self, "_remat_policy", None), self._remat)
        if _engine.is_recording():
            policy = "none"
        if self._scan_layers and not _engine.is_recording():
            x = _scan_layers_call(list(self.layers), x, mask, policy)
        else:
            x = _stack_call(list(self.layers), x, mask, policy)
        # pin the encoder output (and via transpose its cotangent) to batch
        # sharding: the MLM gather and pooler-slice backward paths otherwise
        # propagate conflicting feature shardings from fsdp-sharded head
        # weights onto d(seq), which GSPMD resolves by full remat
        from ..ndarray import apply_op
        from ..parallel import specs as _specs
        x = apply_op(_specs.constrain_batch, x)
        pooled = self.pooler(F.slice_axis(x, axis=1, begin=0, end=1).squeeze(axis=1))
        return x, pooled


class BERTEmbedStage(HybridBlock):
    """BERT embeddings as pipeline stage 0 (word + type + position + LN).
    sp-aware like BERTModel: under a shard_map that controls `sp` it embeds
    this device's sequence shard with the correct global positions.

    `token_types` is optional: the pipeline activation carrier moves a
    single tensor between stages, so segment-free LM pretraining passes
    tokens only — but two-segment pretraining CAN pass token_types and get
    the same embedding sum as BERTModel."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        units, dtype = cfg["units"], cfg["dtype"]
        self._seq_parallel = cfg.get("seq_parallel", False)
        self.word_embed = nn.Embedding(cfg["vocab_size"], units, dtype=dtype,
                                       weight_initializer="xavier")
        self.token_type_embed = nn.Embedding(
            cfg.get("type_vocab_size", 2), units, dtype=dtype,
            weight_initializer="xavier")
        self.position_embed = Parameter(
            "position_weight", shape=(cfg["max_length"], units), dtype=dtype,
            init="xavier")
        self.position_embed.shard_hint = "embedding"
        self.embed_ln = nn.LayerNorm(in_channels=units)

    def forward(self, inputs, token_types=None):
        from ..parallel import in_manual
        L = inputs.shape[1]
        sp_manual = self._seq_parallel and in_manual("sp")
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = x + _positions(self.position_embed, L, sp_manual).expand_dims(axis=0)
        return self.embed_ln(x)


def bert_pipeline_stages(cfg, num_stages):
    """Split a BERT encoder into pipeline stage blocks: stage 0 =
    embeddings, stages 1..S-1 = equal groups of encoder layers. Padding
    masks don't travel the activation carrier, so stages attend over the
    full (micro)batch sequence.

    Use with the hetero PipelineTrainer only on sp=1 meshes. For sequence
    parallelism, build homogeneous stages (BERTEmbedStage + identical
    BERTEncoderLayer stages) for SeqPipelineTrainer instead — ring
    attention's collectives cannot live inside the hetero stage switch."""
    layers_per = cfg["num_layers"] // (num_stages - 1)
    if layers_per * (num_stages - 1) != cfg["num_layers"]:
        raise ValueError(
            f"num_layers {cfg['num_layers']} not divisible into "
            f"{num_stages - 1} encoder stages")
    stages = [BERTEmbedStage(cfg)]
    for _ in range(num_stages - 1):
        seq = nn.HybridSequential()
        for _ in range(layers_per):
            seq.add(BERTEncoderLayer(
                cfg["units"], cfg["hidden_size"], cfg["num_heads"],
                cfg["dropout"], cfg["dtype"],
                attn_dropout=cfg.get("attn_dropout"),
                seq_parallel=cfg.get("seq_parallel", False)))
        stages.append(seq)
    return stages


class BERTForPretraining(HybridBlock):
    """MLM + NSP heads (reference: gluonnlp BERTForPretrain)."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        units, vocab = cfg["units"], cfg["vocab_size"]
        self.bert = BERTModel(**cfg)
        self.mlm_transform = nn.Dense(units, in_units=units, flatten=False,
                                      activation=None, dtype=cfg["dtype"],
                                      weight_initializer="xavier")
        self.mlm_ln = nn.LayerNorm(in_channels=units)
        # decoder weight tied to word embedding; separate bias
        self.mlm_bias = Parameter("mlm_bias", shape=(vocab,), init="zeros")
        self.nsp = nn.Dense(2, in_units=units, dtype=cfg["dtype"],
                            weight_initializer="xavier")

    def forward(self, inputs, token_types, valid_length, masked_positions):
        """Returns (mlm_scores (B,P,V), nsp_scores (B,2))."""
        import jax.numpy as jnp
        from ..ndarray import apply_op
        from ..parallel import specs as _specs
        seq, pooled = self.bert(inputs, token_types, valid_length)
        # gather masked positions before the vocab matmul: (B, P, E).
        # constrain_batch pins the gather output (and, via transpose, the
        # scatter cotangent into seq) to batch sharding so fsdp weight
        # shardings downstream can't force a GSPMD full-remat reshard.
        gathered = apply_op(
            lambda s, p: _specs.constrain_batch(
                jnp.take_along_axis(s, p.astype(jnp.int32)[..., None], 1)),
            seq, masked_positions)
        h = self.mlm_transform(gathered)
        h = F.Activation(h, act_type="gelu")
        h = self.mlm_ln(h)
        scores = apply_op(
            lambda hh, w, b: jnp.matmul(hh, w.T) + b,
            h, self.bert.word_embed.weight.data(), self.mlm_bias.data())
        return scores, self.nsp(pooled)


class BERTForQuestionAnswering(HybridBlock):
    """SQuAD-style span-extraction head (reference: gluonnlp
    BertForQA, scripts/bert/finetune_squad.py — the BASELINE SQuAD-F1
    quality-gate workload): a single Dense projects each token to
    (start, end) logits."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        self.bert = BERTModel(**cfg)
        self.span = nn.Dense(2, in_units=cfg["units"], flatten=False,
                             dtype=cfg["dtype"], weight_initializer="xavier")

    def forward(self, inputs, token_types, valid_length=None):
        """Returns (start_logits (B, L), end_logits (B, L)); positions past
        valid_length are masked to -inf so softmax ignores padding."""
        import jax.numpy as jnp
        from ..ndarray import apply_op
        seq, _ = self.bert(inputs, token_types, valid_length)
        logits = self.span(seq)                      # (B, L, 2)

        def split_mask(lg, vl=None):
            start, end = lg[..., 0], lg[..., 1]
            if vl is not None:
                L = lg.shape[1]
                live = jnp.arange(L)[None, :] < vl[:, None].astype(jnp.int32)
                start = jnp.where(live, start, -1e9)
                end = jnp.where(live, end, -1e9)
            return start, end

        if valid_length is None:
            return apply_op(split_mask, logits)
        return apply_op(split_mask, logits, valid_length)


def bert_qa_loss(start_logits, end_logits, start_positions, end_positions):
    """Mean cross-entropy of the gold start/end positions (reference:
    finetune_squad.py loss)."""
    import jax
    import jax.numpy as jnp
    from ..ndarray import apply_op

    def one(lg, pos):
        logp = jax.nn.log_softmax(lg, axis=-1)
        return -jnp.take_along_axis(
            logp, pos.astype(jnp.int32)[:, None], 1).mean()

    a = apply_op(one, start_logits, start_positions)
    b = apply_op(one, end_logits, end_positions)
    return (a + b) / 2


class BERTClassifier(HybridBlock):
    """Sentence(-pair) classification head over the pooled output
    (reference: gluonnlp BERTClassifier, finetune_classifier.py)."""

    def __init__(self, cfg, num_classes=2, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        self.bert = BERTModel(**cfg)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.classifier = nn.Dense(num_classes, in_units=cfg["units"],
                                   dtype=cfg["dtype"],
                                   weight_initializer="xavier")

    def forward(self, inputs, token_types, valid_length=None):
        _, pooled = self.bert(inputs, token_types, valid_length)
        if self.dropout is not None:
            pooled = self.dropout(pooled)
        return self.classifier(pooled)


def bert_pretrain_loss(mlm_scores, nsp_scores, mlm_labels, mlm_weights, nsp_labels):
    """Pretraining loss on NDArrays (ShardedTrainer loss_fn AND eager
    autograd compatible). mlm_scores (B,P,V), mlm_labels (B,P),
    mlm_weights (B,P) 1 for real masked positions, nsp_labels (B,).
    """
    import jax
    import jax.numpy as jnp
    from ..ndarray import apply_op

    def compute(ms, ns, lbl, w, nl):
        logp = jax.nn.log_softmax(ms.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, lbl.astype(jnp.int32)[..., None], -1)[..., 0]
        w = w.astype(jnp.float32)
        mlm_loss = jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
        nlogp = jax.nn.log_softmax(ns.astype(jnp.float32), -1)
        nsp_loss = -jnp.mean(
            jnp.take_along_axis(nlogp, nl.astype(jnp.int32)[:, None], -1))
        return mlm_loss + nsp_loss

    return apply_op(compute, mlm_scores, nsp_scores, mlm_labels, mlm_weights,
                    nsp_labels)


def tp_rules(tp_axis="tp"):
    """Megatron sharding for BERT params (apply via parallel.apply_tp_rules):
    QKV and FFN-in split over heads/hidden (dim 0 of (out,in) weights),
    proj and FFN-out split on input dim; word embedding split on the FEATURE
    dim (not vocab: a vocab-sharded gather forces GSPMD full
    rematerialization; feature sharding partitions the gather trivially and
    the tied MLM decoder contracts over the sharded dim with a psum)."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"\.qkv\.weight$", P(tp_axis, None)),
        (r"\.qkv\.bias$", P(tp_axis)),
        (r"\.ffn_in\.weight$", P(tp_axis, None)),
        (r"\.ffn_in\.bias$", P(tp_axis)),
        (r"\.proj\.weight$", P(None, tp_axis)),
        (r"\.ffn_out\.weight$", P(None, tp_axis)),
        (r"word_embed\.weight$", P(None, tp_axis)),
    ]


def make_synthetic_batch(cfg, batch_size, seq_len, num_masked=20, seed=0):
    """Deterministic synthetic pretraining batch (zero-egress environments)."""
    rng = np.random.RandomState(seed)
    V = cfg["vocab_size"]
    data = dict(
        input_ids=rng.randint(0, V, (batch_size, seq_len)).astype(np.int32),
        token_types=(rng.rand(batch_size, seq_len) > 0.5).astype(np.int32),
        valid_length=np.full((batch_size,), seq_len, np.int32),
        masked_positions=np.stack(
            [rng.choice(seq_len, num_masked, replace=False)
             for _ in range(batch_size)]).astype(np.int32),
        mlm_labels=rng.randint(0, V, (batch_size, num_masked)).astype(np.int32),
        mlm_weights=np.ones((batch_size, num_masked), np.float32),
        nsp_labels=rng.randint(0, 2, (batch_size,)).astype(np.int32),
    )
    return data
