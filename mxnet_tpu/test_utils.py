"""Test oracles (reference: `python/mxnet/test_utils.py`).

The two universal oracles of the reference test suite (SURVEY.md §4):
`check_numeric_gradient` (finite differences vs autograd backward) and
`check_consistency` (same op, different execution paths cross-compared —
here: eager vs jit vs f64 numpy where applicable).
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from . import autograd
from .ndarray import NDArray

__all__ = ["assert_almost_equal", "check_numeric_gradient", "check_consistency",
           "default_rtol_atol", "rand_ndarray"]


def default_rtol_atol(dtype):
    dt = np.dtype(dtype)
    if dt.itemsize == 2:  # float16 / bfloat16
        return 1e-2, 1e-2
    if dt == np.float32:
        return 1e-4, 1e-5
    return 1e-6, 1e-8


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a, b = _to_np(a), _to_np(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(a.dtype)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} != {names[1]}")


def rand_ndarray(shape, dtype="float32", scale=1.0):
    return nd.array(np.random.normal(0, scale, size=shape).astype(dtype))


def check_numeric_gradient(f, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference check of `f`'s backward.

    f: callable taking NDArrays, returning a single NDArray output.
    inputs: list of numpy arrays (float32 recommended; computed in f64 FD).
    """
    arrs = [nd.array(x.astype(np.float32)) for x in inputs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = f(*arrs)
        loss = out.sum() if out.shape != () else out
    loss.backward()
    sym_grads = [a.grad.asnumpy() for a in arrs]

    def fval(xs):
        with autograd.pause():
            return float(f(*[nd.array(x.astype(np.float32)) for x in xs]).sum().asscalar())

    for i, x in enumerate(inputs):
        num = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        nflat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            xs = [v.copy() for v in inputs]
            xs[i].reshape(-1)[j] = orig + eps
            fp = fval(xs)
            xs[i].reshape(-1)[j] = orig - eps
            fm = fval(xs)
            nflat[j] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(
            sym_grads[i], num, rtol=rtol, atol=atol,
            err_msg=f"numeric vs autograd gradient mismatch for input {i}")


def check_consistency(f, inputs, rtol=1e-5, atol=1e-6):
    """Run `f` eagerly and under jax.jit and compare outputs (the TPU-native
    analog of the reference's cpu-vs-gpu-vs-cudnn `check_consistency`)."""
    import jax

    arrs = [nd.array(x) for x in inputs]
    eager = f(*arrs)
    eager_np = [_to_np(o) for o in (eager if isinstance(eager, (list, tuple)) else [eager])]

    def pure(*datas):
        outs = f(*[NDArray(d) for d in datas])
        if isinstance(outs, (list, tuple)):
            return tuple(o._data for o in outs)
        return outs._data

    jitted = jax.jit(pure)(*[a._data for a in arrs])
    jit_np = [np.asarray(o) for o in (jitted if isinstance(jitted, tuple) else [jitted])]
    for e, j in zip(eager_np, jit_np):
        np.testing.assert_allclose(e, j, rtol=rtol, atol=atol,
                                   err_msg="eager vs jit inconsistency")
