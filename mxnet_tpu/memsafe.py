"""mx.memsafe — never-OOM execution.

On a TPU an out-of-memory is an opaque `RESOURCE_EXHAUSTED` that kills the
whole gang mid-run; the information to predict it existed BEFORE dispatch
(`mx.inspect` computes per-executable peak device bytes from XLA's own
`memory_analysis()`, and `device.memory_stats()` reports the capacity).
This module uses that information proactively — "Memory Safe Computations
with XLA Compiler" (PAPERS.md, arxiv 2206.14148) — in four pieces:

  * **pre-flight budget check** — on every jit-cache miss (HybridBlock
    `_call_cached` and the ShardedTrainer step cache), the freshly built
    computation is lowered + compiled ANALYTICALLY and its execution
    footprint beyond the arguments (output + temp - donated bytes) plus
    the resident state (params, optimizer moments, aux, the staged batch
    — the argument buffers, counted exactly once) is compared against
    the device capacity (`device_bytes_limit` knob, else
    `device.memory_stats()['bytes_limit']`). A predicted overrun raises
    `MemoryBudgetError` naming the executable, the predicted peak, the
    capacity, the shortfall, and concrete remediations — BEFORE any device
    dispatch, so no half-donated train state is lost. Every check feeds the
    `memory_headroom_bytes` gauge; headroom below a `memory_headroom_warn`
    fraction of capacity emits a warning event.
  * **graduated remat policies** — `HybridBlock.remat(policy=...)` with
    `"none" | "dots_saveable" | "layers" | "full"` (increasing memory
    savings, increasing recompute), mapped onto `jax.checkpoint` policies;
    the `remat_policy` knob applies a default to every block and the
    per-model `remat=True` config flags keep working as the `"layers"`
    alias.
  * **graceful OOM degradation** — with `oom_recover=auto`, a
    RESOURCE_EXHAUSTED (or pre-flight MemoryBudgetError) at the trainer
    step boundary walks a degradation ladder instead of crashing: escalate
    the remat policy one rung, then shard the optimizer state across the
    data replicas (mx.zero — bit-identical values, (D-1)/D of the
    opt-state bytes back), then halve the effective batch via
    gradient-accumulation microbatching (loss/grad parity preserved up to
    reduction order), re-plan, retry. Each transition is logged to
    telemetry, the diagnostics flight ring, and the post-mortem "memsafe"
    section. `oom_recover=off` (default) keeps today's fail-fast behavior.
  * **auto-fit** — `dataflow.autofit(...)` (+ the `tools/autofit.py` CLI)
    binary-searches the largest batch / `BucketPad` bucket configuration
    whose PREDICTED peak fits the measured capacity, using AOT lowering +
    `memory_analysis()` only — no device step executes.

Cost model: DISABLED (the default) is the production fast path — the
trainer/block hook sites check one module-level bool and fall through; no
analysis compile, no capacity probe, no recovery handler (`ci/run.sh
sanity` asserts it). ENABLED costs one extra lower+compile per jit-cache
miss (served warm from the persistent XLA cache when `compile_cache_dir`
is set) — the same trade `mx.inspect` makes.
"""
from __future__ import annotations

import sys
import time

from . import _locklint

from . import config as _config
from . import diagnostics as _diagnostics
from . import goodput as _goodput
from . import telemetry as _telemetry

__all__ = [
    "enable", "disable", "enabled", "maybe_enable", "reset",
    "MemoryBudgetError", "SimulatedResourceExhausted", "is_oom",
    "capacity_bytes", "resident_bytes", "compiled_exec_peak",
    "aot_exec_peak", "preflight_step", "preflight_jit", "check_budget",
    "POLICIES", "LADDER", "validate_policy", "effective_policy",
    "jax_policy", "policy_marker", "block_wrap_policy",
    "recover_trainer", "note_eager_oom", "transitions", "last_check",
    "last_headroom_bytes", "snapshot",
]

_lock = _locklint.make_rlock("memsafe.state")
_enabled = False              # the fast-path bool; hook sites read it directly
_last_check = None            # dict of the most recent pre-flight check
_transitions = []             # degradation-ladder transitions this process
_oom_events = 0
_warned = set()               # executables already headroom-warned (no spam)

_M_HEADROOM = _telemetry.gauge(
    "memory_headroom_bytes", "device capacity minus the predicted peak of "
    "the last pre-flight-checked executable (resident state + execution "
    "peak); negative would have been an OOM — the check raises instead")
_M_OOM_EVENTS = _telemetry.counter(
    "oom_events_total", "out-of-memory events seen at the trainer boundary: "
    "device RESOURCE_EXHAUSTED plus pre-flight MemoryBudgetError rejections")
_M_OOM_RECOVERIES = _telemetry.counter(
    "oom_recoveries_total", "OOM events survived by the oom_recover=auto "
    "degradation ladder (the step completed after remat escalation and/or "
    "gradient-accumulation microbatching)")


class MemoryBudgetError(RuntimeError):
    """Pre-flight budget check predicted an out-of-memory: the executable's
    predicted peak (execution peak + resident state) exceeds the device
    capacity. Raised BEFORE any device dispatch — no train state has been
    donated or lost. Carries the accounting so tooling (and the
    oom_recover=auto ladder) can act on it."""

    def __init__(self, executable, predicted_bytes, capacity_bytes,
                 exec_peak_bytes=None, resident_bytes=None):
        self.executable = executable
        self.predicted_bytes = int(predicted_bytes)
        self.capacity_bytes = int(capacity_bytes)
        self.exec_peak_bytes = exec_peak_bytes
        self.resident_bytes = resident_bytes
        self.headroom_bytes = int(capacity_bytes) - int(predicted_bytes)
        short = -self.headroom_bytes
        parts = ""
        if exec_peak_bytes is not None and resident_bytes is not None:
            parts = (f" ({_fmt(exec_peak_bytes)} execution peak + "
                     f"{_fmt(resident_bytes)} resident params/optimizer/"
                     "batch)")
        super().__init__(
            f"predicted peak device memory for executable '{executable}' is "
            f"{_fmt(predicted_bytes)}{parts} but device capacity is "
            f"{_fmt(capacity_bytes)} — {_fmt(short)} short. Remediations, "
            "cheapest first: (1) rematerialization — "
            "block.remat(policy='dots_saveable'|'layers'|'full') or the "
            "remat_policy knob trades recompute for activation memory; "
            "(2) shard optimizer state across the data replicas — set "
            "zero=auto (mx.zero) or trainer.set_zero(True): resident "
            "opt-state bytes drop by (D-1)/D with values unchanged; "
            "(3) a smaller batch or BucketPad bucket — dataflow.autofit() "
            "binary-searches the largest configuration that fits. "
            "Set oom_recover=auto to walk these "
            "automatically, or raise device_bytes_limit if the simulated "
            "capacity is wrong.")


class SimulatedResourceExhausted(RuntimeError):
    """Synthetic device OOM raised by the FaultInjector `oom@step:N` spec
    (mx.resilience): the message carries the literal RESOURCE_EXHAUSTED
    marker so it classifies exactly like the real jaxlib error, but no
    device state was touched — every rung of the degradation ladder is
    drivable in CPU tests."""

    def __init__(self, step=None):
        super().__init__(
            "RESOURCE_EXHAUSTED: synthetic out-of-memory injected by "
            f"mx.resilience fault_inject oom@step:{step} (no device "
            "allocation actually failed)")


def _fmt(n):
    """Human bytes for error messages: '1.50 GiB (1610612736 bytes)'."""
    from .util import fmt_bytes
    return fmt_bytes(n, show_raw=True)


def is_oom(exc):
    """True for anything the degradation ladder can act on: a device
    RESOURCE_EXHAUSTED (real jaxlib XlaRuntimeError or the injected
    synthetic) or the pre-flight MemoryBudgetError."""
    return isinstance(exc, MemoryBudgetError) or \
        "RESOURCE_EXHAUSTED" in str(exc)


# ---------------------------------------------------------------------------
# enable / disable
# ---------------------------------------------------------------------------

def enabled():
    """True when memsafe is armed (hook sites read the module global
    `_enabled` directly — this accessor is the public spelling)."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def maybe_enable():
    """Arm memsafe iff the knobs ask for it (`oom_recover=auto` or a
    positive `device_bytes_limit`). Called at trainer construction so
    `mx.config.set(...)` after import still takes effect; one or two dict
    reads, construction-time only — never on the step hot path."""
    if _enabled:
        return True
    if _config.get("oom_recover") == "auto" \
            or int(_config.get("device_bytes_limit")) > 0:
        enable()
    return _enabled


def reset():
    """Drop recorded checks/transitions (tests and run boundaries)."""
    global _last_check, _oom_events
    with _lock:
        _last_check = None
        _oom_events = 0
        del _transitions[:]
        _warned.clear()


# ---------------------------------------------------------------------------
# capacity + accounting
# ---------------------------------------------------------------------------

def capacity_bytes():
    """Device memory capacity in bytes: the `device_bytes_limit` knob when
    positive (CPU CI and tests simulate any capacity this way), else the
    first local device's memory_stats()['bytes_limit'], else None (backend
    reports nothing — CPU — and no check can run). Never cold-inits a
    backend."""
    knob = int(_config.get("device_bytes_limit"))
    if knob > 0:
        return knob
    devs = _diagnostics._jax_devices_if_initialized()
    if not devs:
        return None
    try:
        stats = devs[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def resident_bytes(*trees):
    """Total PER-DEVICE bytes of every array leaf in the given pytrees —
    the state that stays resident on each device while the executable
    runs (params, optimizer moments, aux, the staged batch). A sharded
    array (mx.zero optimizer state, fsdp params, a sharded batch) counts
    only its per-device shard, not the global array: that is what each
    device actually keeps, and what the budget check must compare against
    per-chip capacity. Replicated arrays count in full."""
    import math

    import jax
    total = 0
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            try:
                nbytes = int(leaf.nbytes)
            except Exception:
                # typed PRNG keys (and other extended dtypes) refuse
                # .nbytes; they are a handful of words — negligible
                continue
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                try:
                    shard = sharding.shard_shape(tuple(leaf.shape))
                    nbytes = int(math.prod(shard)) * leaf.dtype.itemsize
                except Exception:
                    pass    # host arrays / odd shardings: global count
            total += nbytes
    return total


def compiled_exec_peak(compiled):
    """Execution-time bytes one compiled executable needs ON TOP of its
    resident argument buffers: output + temp - donated (donated arguments
    alias into outputs, so their reuse is not new memory). The arguments
    themselves are counted exactly once, by resident_bytes — summing
    XLA's full peak (which includes arguments) with the resident state
    would double-count every non-donated buffer and falsely reject
    configurations that fit. None when the backend withholds any
    component. Never raises."""
    from . import inspect as _inspect
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    _arg, out, tmp, alias, peak = _inspect.memory_breakdown(mem)
    if peak is None:
        return None
    return max(0, out + tmp - (alias or 0))


# ---------------------------------------------------------------------------
# pre-flight budget check
# ---------------------------------------------------------------------------

def check_budget(executable, exec_peak, resident, capacity=None):
    """Compare one executable's predicted peak (execution peak + resident
    state) against capacity. Records the check (last_check / the
    memory_headroom_bytes gauge), warns when headroom drops below the
    `memory_headroom_warn` fraction of capacity, and raises
    MemoryBudgetError on a predicted overrun. `exec_peak` None (analysis
    unavailable) checks resident state alone."""
    global _last_check
    capacity = capacity if capacity is not None else capacity_bytes()
    predicted = int(resident or 0) + int(exec_peak or 0)
    headroom = None if capacity is None else int(capacity) - predicted
    with _lock:
        _last_check = {
            "executable": executable,
            "exec_peak_bytes": exec_peak,
            "resident_bytes": int(resident or 0),
            "predicted_bytes": predicted,
            "capacity_bytes": capacity,
            "headroom_bytes": headroom,
            "ts": time.time(),
        }
    if capacity is None:
        return _last_check
    if _telemetry._enabled:
        _M_HEADROOM.set(headroom)
    if headroom < 0:
        _count_oom("budget", executable)
        raise MemoryBudgetError(executable, predicted, capacity,
                                exec_peak_bytes=exec_peak,
                                resident_bytes=int(resident or 0))
    warn_frac = float(_config.get("memory_headroom_warn"))
    if warn_frac > 0 and headroom < warn_frac * capacity \
            and executable not in _warned:
        _warned.add(executable)
        print(f"mx.memsafe: WARNING — executable '{executable}' leaves only "
              f"{_fmt(headroom)} headroom ({headroom / capacity:.1%} of "
              f"capacity, warn threshold {warn_frac:.1%}); one larger bucket "
              "or a fragmentation spike away from RESOURCE_EXHAUSTED",
              file=sys.stderr)
        if _telemetry._enabled:
            _telemetry.event("memsafe_warning", executable=executable,
                             headroom_bytes=headroom,
                             predicted_bytes=predicted,
                             capacity_bytes=capacity)
        if _diagnostics._enabled:
            _diagnostics.record_event(
                "memsafe_warning", executable=executable,
                headroom_bytes=headroom, predicted_bytes=predicted)
    return _last_check


def _analyze(jitted, args, traced=None):
    """AOT lower+compile purely for memory analysis;
    (exec_peak, compiled, error). With compile_cache_dir set the real
    first call deserializes this same executable warm. Never raises — a
    backend that cannot lower out of line degrades the check to
    resident-state accounting. `traced`: a pre-computed jax Traced (from
    mx.check's lint of the same miss) lowered directly, so check+memsafe
    together cost one trace per miss, not two."""
    try:
        if traced is not None:
            try:
                compiled = traced.lower().compile()
                return compiled_exec_peak(compiled), compiled, None
            except Exception:   # stale/unlowerable trace: re-derive
                pass
        compiled = jitted.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 — degrade, never block dispatch
        return None, None, f"{type(e).__name__}: {e}"
    return compiled_exec_peak(compiled), compiled, None


def aot_exec_peak(jitted, args):
    """Execution-peak bytes of `jitted` AOT lowered+compiled at `args`
    (concrete arrays or jax.ShapeDtypeStructs) — the public spelling of
    the analysis `_analyze` runs at every preflight, for callers that
    budget BEFORE building state (mx.serve admission control sizes KV
    caches this way; `ShardedTrainer.predict_step_bytes` is the training
    twin). Nothing is dispatched; with compile_cache_dir set the real
    first call deserializes the same executable warm. None when the
    backend withholds analysis — never raises."""
    peak, _compiled, _err = _analyze(jitted, args)
    return peak


def _preflight(name, key, jitted, args, collectives=None, traced=None):
    """Shared preflight body: with no known capacity there is nothing to
    check, so the (expensive) analysis compile is skipped entirely and
    only the resident accounting is recorded. When the analysis does run
    and mx.inspect is enabled, the compiled object is handed to inspect's
    registry too — the pair then costs ONE extra compile per miss, not
    two (the hook sites skip their own analyze_jit via the returned
    'inspect_recorded' flag). `traced` likewise shares mx.check's trace."""
    capacity = capacity_bytes()
    resident = resident_bytes(args)
    if capacity is None:
        return check_budget(name, None, resident, capacity=None)
    exec_peak, compiled, err = _analyze(jitted, args, traced=traced)
    check = check_budget(name, exec_peak, resident, capacity=capacity)
    if err is not None:
        check["analysis_error"] = err
    if compiled is not None:
        from . import inspect as _inspect
        if _inspect._enabled:
            _inspect.record_compiled(name, _inspect.key_repr(key), compiled,
                                     collectives=collectives)
            check["inspect_recorded"] = True
    return check


def preflight_step(trainer, key, jitted, args, traced=None):
    """Pre-flight budget check for one freshly built ShardedTrainer step
    executable, BEFORE its first dispatch: AOT-analyze the execution
    footprint, add the resident train state + staged batch (== the call
    args), and check the budget. Raises MemoryBudgetError on a predicted
    overrun (nothing was dispatched; donated buffers are intact)."""
    name = f"ShardedTrainer({type(trainer.block).__name__})"
    return _preflight(name, key, jitted, args,
                      collectives=getattr(trainer, "_coll_est", None),
                      traced=traced)


def preflight_jit(name, key, jitted, args, traced=None):
    """Pre-flight check for one freshly built HybridBlock executable
    (forward path): resident state is the parameters + inputs the call
    will hold live."""
    return _preflight(name, key, jitted, args, traced=traced)


def last_check():
    """The most recent pre-flight check's accounting dict (None before
    any)."""
    with _lock:
        return dict(_last_check) if _last_check else None


def last_headroom_bytes():
    """Headroom recorded by the most recent pre-flight check (None before
    any check, or when capacity was unknown)."""
    with _lock:
        return _last_check.get("headroom_bytes") if _last_check else None


# ---------------------------------------------------------------------------
# graduated remat policies
# ---------------------------------------------------------------------------

#: valid policies, in INCREASING memory savings (and recompute cost):
#:   none          — save every intermediate (fastest backward, most HBM)
#:   dots_saveable — jax.checkpoint saving matmul/dot outputs, recomputing
#:                   elementwise/normalization work (the cheap recompute)
#:   layers        — per-layer jax.checkpoint saving ONLY layer boundaries;
#:                   activation memory O(1) in depth (the classic trade)
#:   full          — one checkpoint around the whole stack on top of the
#:                   per-layer ones: only the model inputs survive forward
POLICIES = ("none", "dots_saveable", "layers", "full")

#: the oom_recover=auto escalation order (same tuple; alias for intent)
LADDER = POLICIES


def validate_policy(policy):
    if policy not in POLICIES:
        raise ValueError(
            f"remat policy {policy!r}: expected one of {POLICIES}")
    return policy


def effective_policy(explicit, legacy=False):
    """Resolve the policy for one block: an explicit `.remat(policy=...)`
    wins, else the `remat_policy` knob's global default, else the legacy
    boolean `remat=` config flag as the 'layers' alias, else 'none'."""
    if explicit:
        return validate_policy(explicit)
    knob = _config.get("remat_policy")
    if knob:
        return validate_policy(knob)
    return "layers" if legacy else "none"


def jax_policy(policy):
    """The `jax.checkpoint(policy=...)` argument for one policy name:
    dots_saveable maps to jax's own policy object; layers/full save
    nothing (None) — their structure comes from WHERE the checkpoint is
    applied, not what it saves."""
    if policy == "dots_saveable":
        import jax
        return jax.checkpoint_policies.dots_saveable
    return None


def _policy_block(block):
    """The first block in the subtree that consumes remat policies
    structurally (BERTModel/GPTModel: per-layer / scan-body checkpointing),
    or None when the subtree has no structural handler."""
    if getattr(block, "_remat_handles_policy", False):
        return block
    for child in getattr(block, "_children", {}).values():
        found = _policy_block(child)
        if found is not None:
            return found
    return None


def policy_marker(block):
    """The effective remat policy string for a block tree — what the
    trainer step-cache key carries so a policy change re-jits, and what
    bench reports."""
    b = _policy_block(block) or block
    return effective_policy(getattr(b, "_remat_policy", None),
                            bool(getattr(b, "_remat", False)))


def block_wrap_policy(block):
    """Policy to apply around a block's WHOLE pure function (the generic
    fallback for blocks without structural layer handling), or None. A
    structural handler anywhere in the subtree owns the policy instead —
    wrapping the root too would double-checkpoint."""
    if _policy_block(block) is not None:
        return None
    pol = effective_policy(getattr(block, "_remat_policy", None), False)
    return None if pol == "none" else pol


# ---------------------------------------------------------------------------
# graceful OOM degradation (the ladder)
# ---------------------------------------------------------------------------

def _count_oom(kind, executable=None, step=None):
    global _oom_events
    with _lock:
        _oom_events += 1
    if _telemetry._enabled:
        _M_OOM_EVENTS.inc()
        _telemetry.event("oom", cause=kind, executable=executable, step=step)
    if _diagnostics._enabled:
        _diagnostics.record_event("oom", cause=kind, executable=executable,
                                  step=step)


def _state_intact(trainer):
    """False when the failed dispatch consumed the donated train state (a
    real device OOM mid-execution) — nothing left to retry with."""
    import jax
    leaves = jax.tree_util.tree_leaves(
        (trainer.params, trainer.aux, trainer.opt_state))
    return all(not (hasattr(leaf, "is_deleted") and leaf.is_deleted())
               for leaf in leaves)


def _zero_rung_available(trainer):
    """True when the 'enable mx.zero' rung can fire: the trainer is not
    already sharding optimizer state and its mesh/state could (lazy
    import: memsafe must not pull the parallel package at import)."""
    if getattr(trainer, "_zero", False) or not hasattr(trainer, "set_zero"):
        return False
    try:
        from .parallel import zero as _zero
        return _zero.eligible(trainer)
    except Exception:
        return False


def _next_rung(trainer, data, labels):
    """The next degradation to try: escalate the remat policy one rung
    while possible, then shard the optimizer state across the data
    replicas (mx.zero — a pure layout change, bit-identical values,
    (D-1)/D of the opt-state bytes back), then double the gradient-
    accumulation factor while the batch still divides. None when the
    ladder is exhausted."""
    cur = policy_marker(trainer.block)
    if hasattr(trainer.block, "remat") and cur in LADDER \
            and cur != LADDER[-1]:
        return ("remat", LADDER[LADDER.index(cur) + 1])
    if _zero_rung_available(trainer):
        return ("zero", True)
    data = data if isinstance(data, (list, tuple)) else [data]
    labels = labels if isinstance(labels, (list, tuple)) else [labels]
    new_accum = int(getattr(trainer, "_accum", 1)) * 2
    shapes = [tuple(getattr(b, "shape", ())) for b in
              list(data) + list(labels)]
    # every array needs a splittable leading dim — a 0-d scalar anywhere
    # makes _build_step reject the accum rung, so don't propose it
    if shapes and new_accum <= 256 and \
            all(s and s[0] % new_accum == 0 and s[0] // new_accum >= 1
                for s in shapes):
        return ("accum", new_accum)
    return None


def _note_transition(trainer, kind, value, step):
    entry = {"kind": kind, "value": value, "step": step, "ts": time.time(),
             "policy": policy_marker(trainer.block),
             "accum": int(getattr(trainer, "_accum", 1)),
             "zero": bool(getattr(trainer, "_zero", False))}
    with _lock:
        _transitions.append(entry)
    if kind == "remat":
        what = f"remat policy -> {value!r}"
    elif kind == "zero":
        what = ("optimizer-state sharding ON (mx.zero: reduce-scatter/"
                "all-gather weight update; values unchanged, resident "
                "opt-state bytes /= data extent)")
    else:
        what = (f"gradient accumulation x{value} (microbatch = batch/"
                f"{value})")
    print(f"mx.memsafe: degradation ladder at step {step}: {what}",
          file=sys.stderr)
    if _telemetry._enabled:
        _telemetry.event("memsafe", action=kind, value=value, step=step)
    if _diagnostics._enabled:
        _diagnostics.record_event("memsafe", action=kind, value=value,
                                  step=step)


def recover_trainer(trainer, exc, data, labels, fence_every):
    """Walk the degradation ladder after an OOM at the trainer step
    boundary (called by ShardedTrainer._step_impl; memsafe enabled and
    is_oom(exc) already established). With oom_recover != 'auto' the
    original error propagates untouched (fail-fast). Otherwise: escalate
    remat, then halve the batch via gradient accumulation, re-plan (the
    step cache re-jits under the new key) and retry, until the step
    completes or the ladder is exhausted.

    Note on RNG: a failed attempt may have consumed a step key from the
    global stream before dying, so a recovered DROPOUT run's draws can
    shift relative to an uninterrupted one — losses stay valid, they are
    just a different sample. Deterministic-parity tests run dropout-free."""
    step = int(trainer.num_update) + 1
    t_rung = time.perf_counter() if _goodput._enabled else None
    if not isinstance(exc, MemoryBudgetError):
        # pre-flight rejections already counted themselves in check_budget
        _count_oom("device", step=step)
    if _config.get("oom_recover") != "auto":
        raise exc
    if not _state_intact(trainer):
        # the failed dispatch consumed donated buffers: values are gone,
        # a retry would compute garbage. The pre-flight check exists to
        # catch this case BEFORE dispatch.
        raise RuntimeError(
            "mx.memsafe: the OOM-failed dispatch consumed the trainer's "
            "donated train state — cannot retry in place. Set "
            "device_bytes_limit (or run on a backend with memory_stats) "
            "so the pre-flight budget check rejects the configuration "
            "before dispatch, or restore from the last checkpoint."
        ) from exc
    while True:
        rung = _next_rung(trainer, data, labels)
        if rung is None:
            try:
                exc.add_note("mx.memsafe: degradation ladder exhausted "
                             "(remat at 'full', batch no longer divisible)")
            except AttributeError:  # pragma: no cover - py<3.11
                pass
            raise exc
        kind, value = rung
        if kind == "remat":
            trainer.block.remat(value)
        elif kind == "zero":
            trainer.set_zero(True)
        else:
            trainer.set_grad_accum(value)
        trainer._step_cache.clear()
        _note_transition(trainer, kind, value, step)
        if _goodput._enabled:
            # the ladder walk so far (failed attempt + re-plan) is
            # badput:oom_recovery, and so is the retry's re-jit below
            # (note_oom_begin re-categorizes its cache-miss interval)
            now = time.perf_counter()
            _goodput.note("oom_recovery", t_rung if t_rung is not None
                          else now, now, step=step, rung=kind)
            t_rung = now
            _goodput.note_oom_begin(step)
        try:
            out = trainer._step_once(data, labels, fence_every)
        except Exception as e2:  # noqa: BLE001 — classified below
            if not is_oom(e2):
                raise
            if not isinstance(e2, MemoryBudgetError):
                _count_oom("device", step=step)
            if not _state_intact(trainer):
                raise
            exc = e2
            continue
        if _telemetry._enabled:
            _M_OOM_RECOVERIES.inc()
        print(f"mx.memsafe: step {step} recovered (policy="
              f"{policy_marker(trainer.block)!r}, zero="
              f"{bool(getattr(trainer, '_zero', False))}, grad "
              f"accumulation x{getattr(trainer, '_accum', 1)})",
              file=sys.stderr)
        return out


def note_eager_oom(exc, step=None):
    """Record an OOM on the eager gluon Trainer path (which cannot
    microbatch a tape that already ran) and annotate the exception with
    the remediation story before it propagates."""
    _count_oom("eager", step=step)
    try:
        exc.add_note(
            "mx.memsafe: eager-path OOM — the gluon Trainer cannot degrade "
            "a step whose tape already ran. Remat the model "
            "(block.remat(policy=...)), reduce the batch, or move to "
            "parallel.ShardedTrainer where oom_recover=auto walks the "
            "degradation ladder automatically.")
    except AttributeError:  # pragma: no cover - py<3.11
        pass


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def transitions():
    """Degradation-ladder transitions recorded this process (copies)."""
    with _lock:
        return [dict(t) for t in _transitions]


def snapshot():
    """Plain-data summary for the diagnostics post-mortem 'memsafe'
    section: the last pre-flight check, every ladder transition, and the
    OOM event count."""
    with _lock:
        return {
            "oom_events": _oom_events,
            "last_check": dict(_last_check) if _last_check else None,
            "transitions": [dict(t) for t in _transitions],
        }


maybe_enable()
