"""`mx.io` data iterators (reference: `python/mxnet/io.py` over `src/io/`).

The reference's C++ iterator stack (RecordIO parse → threaded decode/augment
→ batch → prefetch) maps to: recordio.py (format), ImageRecordIter (threaded
decode pool + double-buffer prefetch — host CPU work feeding the TPU), and
NDArrayIter for in-memory data.
"""
from __future__ import annotations

import queue
import threading
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ndarray import NDArray
from ..ndarray import ndarray as _nd
from . import recordio

__all__ = ["DataBatch", "DataDesc", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "ImageRecordIter", "MNISTIter", "CSVIter",
           "LibSVMIter", "recordio"]

DataDesc = namedtuple("DataDesc", ["name", "shape"])


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data if isinstance(data, (list, tuple)) else [data]
        self.label = label if label is None or isinstance(label, (list, tuple)) else [label]
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator protocol of the reference (`next/reset/provide_data`)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def __next__(self):
        return self.next()

    def next(self):
        raise StopIteration

    @property
    def provide_data(self):
        return None

    @property
    def provide_label(self):
        return None


class NDArrayIter(DataIter):
    """In-memory iterator (reference: mx.io.NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self._data = self._init(data, data_name)
        self._label = self._init(label, label_name) if label is not None else []
        self._num = len(self._data[0][1]) if self._data else 0
        self._shuffle = shuffle
        self._last = last_batch_handle
        self.reset()

    @staticmethod
    def _init(src, default_name):
        if src is None:
            return []
        if isinstance(src, (np.ndarray, NDArray)):
            src = {default_name: src}
        elif isinstance(src, (list, tuple)):
            src = {f"{default_name}_{i}" if i else default_name: d
                   for i, d in enumerate(src)}
        out = []
        for name, arr in src.items():
            if isinstance(arr, NDArray):
                arr = arr.asnumpy()
            out.append((name, np.asarray(arr)))
        return out

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + a.shape[1:]) for n, a in self._data]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + a.shape[1:]) for n, a in self._label]

    def reset(self):
        self._cursor = 0
        self._order = np.random.permutation(self._num) if self._shuffle \
            else np.arange(self._num)

    def next(self):
        if self._cursor >= self._num:
            raise StopIteration
        idx = self._order[self._cursor:self._cursor + self.batch_size]
        pad = 0
        if len(idx) < self.batch_size:
            if self._last == "discard":
                raise StopIteration
            pad = self.batch_size - len(idx)
            idx = np.concatenate([idx, self._order[:pad]])
        self._cursor += self.batch_size
        data = [_nd.array(a[idx]) for _, a in self._data]
        label = [_nd.array(a[idx]) for _, a in self._label]
        return DataBatch(data, label, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ResizeIter(DataIter):
    """Fix an iterator to `size` batches per epoch (reference: ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self._iter = data_iter
        self._size = size
        self._reset_internal = reset_internal
        self._cur = 0

    def reset(self):
        self._cur = 0
        if self._reset_internal:
            self._iter.reset()

    def next(self):
        if self._cur >= self._size:
            raise StopIteration
        self._cur += 1
        try:
            return self._iter.next()
        except StopIteration:
            self._iter.reset()
            return self._iter.next()

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label


class PrefetchingIter(DataIter):
    """Double-buffered prefetcher (reference: `src/io/iter_prefetcher.h`)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        it = iters[0] if isinstance(iters, (list, tuple)) else iters
        super().__init__(it.batch_size)
        self._iter = it
        self._queue = queue.Queue(maxsize=2)
        self._thread = None
        self._start()

    def _start(self):
        stop = object()
        self._stop = stop

        def worker():
            while True:
                try:
                    self._queue.put(self._iter.next())
                except StopIteration:
                    self._queue.put(stop)
                    return
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._iter.reset()
        self._queue = queue.Queue(maxsize=2)
        self._start()

    def next(self):
        item = self._queue.get()
        if item is self._stop:
            raise StopIteration
        return item

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label


class ImageRecordIter(DataIter):
    """RecordIO image iterator with threaded decode + augmentation.

    Reference: `src/io/iter_image_recordio_2.cc` (ImageRecordIOParser2):
    N decoder threads → augment (crop/flip) → batch → prefetch. Layout NCHW
    float32 output, optional mean/std normalization.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, path_imgidx=None,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, preprocess_threads=4, round_batch=True,
                 use_native=None, seed=0, num_parts=1, part_index=0,
                 **kwargs):
        from ..base import part_range
        super().__init__(batch_size)
        self._data_shape = tuple(data_shape)  # (C, H, W)
        idx_path = path_imgidx or path_imgrec.rsplit(".", 1)[0] + ".idx"
        self._record = recordio.IndexedRecordIO(idx_path, path_imgrec, "r")
        # multi-worker input sharding (reference: iter_image_recordio_2.cc
        # num_parts/part_index): this worker owns a disjoint key slice
        lo, hi = part_range(len(self._record.keys), num_parts, part_index)
        self._part_keys = list(self._record.keys)[lo:hi]
        self._native = None
        if use_native is not False and self._record.keys:
            # C++ decode/augment/prefetch pipeline (native/), the analog of
            # the reference's ImageRecordIOParser2 fast path; JPEG-only —
            # sniff the first payload before committing to it.
            _, payload = recordio.unpack(
                self._record.read_idx(self._record.keys[0]))
            if payload[:2] == b"\xff\xd8":
                from . import native as _native_mod
                if _native_mod.available():
                    try:
                        self._native = _native_mod.NativeImagePipeline(
                            path_imgrec, idx_path, batch_size,
                            self._data_shape,
                            num_threads=preprocess_threads, shuffle=shuffle,
                            rand_crop=rand_crop, rand_mirror=rand_mirror,
                            mean=[mean_r, mean_g, mean_b],
                            std=[std_r, std_g, std_b], seed=seed,
                            num_parts=num_parts, part_index=part_index)
                    except RuntimeError:
                        self._native = None
        if use_native and self._native is None:
            raise RuntimeError("use_native=True but native pipeline "
                               "could not be initialized")
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._mean = np.array([mean_r, mean_g, mean_b], np.float32).reshape(3, 1, 1)
        self._std = np.array([std_r, std_g, std_b], np.float32).reshape(3, 1, 1)
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self.reset()

    def _decode_one(self, raw):
        # raw record bytes are read serially in next() — the shared file
        # handle's seek/read is not thread-safe; only decode fans out.
        header, payload = recordio.unpack(raw)
        img = recordio.imdecode(payload, 1).astype(np.float32)  # HWC
        C, H, W = self._data_shape
        ih, iw = img.shape[:2]
        if self._rand_crop and ih > H and iw > W:
            y0 = np.random.randint(0, ih - H + 1)
            x0 = np.random.randint(0, iw - W + 1)
        else:
            y0, x0 = max((ih - H) // 2, 0), max((iw - W) // 2, 0)
        img = img[y0:y0 + H, x0:x0 + W]
        if img.shape[0] != H or img.shape[1] != W:  # small image: pad
            canvas = np.zeros((H, W, img.shape[2]), np.float32)
            canvas[:img.shape[0], :img.shape[1]] = img
            img = canvas
        if self._rand_mirror and np.random.rand() < 0.5:
            img = img[:, ::-1]
        chw = np.transpose(img, (2, 0, 1))
        chw = (chw - self._mean[:chw.shape[0]]) / self._std[:chw.shape[0]]
        label = header.label
        if isinstance(label, np.ndarray):
            label = label[0]
        return chw, np.float32(label)

    def reset(self):
        if self._native is not None:
            if getattr(self, "_started", False):
                self._native.reset()
            self._started = True
        keys = list(self._part_keys)
        if self._shuffle:
            np.random.shuffle(keys)
        self._keys = keys
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def next(self):
        if self._native is not None:
            out = self._native.next()
            if out is None:
                raise StopIteration
            data, label, pad = out
            return DataBatch([_nd.array(data)],
                             [_nd.array(label[:, 0])], pad=pad)
        if self._cursor >= len(self._keys):
            raise StopIteration
        keys = self._keys[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        pad = self.batch_size - len(keys)
        if pad:
            keys = keys + self._keys[:pad]
        raws = [self._record.read_idx(k) for k in keys]
        results = list(self._pool.map(self._decode_one, raws))
        data = np.stack([r[0] for r in results])
        label = np.asarray([r[1] for r in results], np.float32)
        return DataBatch([_nd.array(data)], [_nd.array(label)], pad=pad)


class MNISTIter(NDArrayIter):
    """Reference: `src/io/iter_mnist.cc`; reads idx files via gluon MNIST."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=False,
                 flat=False, **kwargs):
        import os
        from ..gluon.data.vision.datasets import MNIST
        root = os.path.dirname(image) if image else "~/.mxnet/datasets/mnist"
        train = image is None or "train" in os.path.basename(image)
        ds = MNIST(root=root, train=train)
        data = ds._data.astype(np.float32) / 255.0
        data = data.reshape(len(data), -1) if flat else \
            np.transpose(data, (0, 3, 1, 2))
        super().__init__(data, ds._label.astype(np.float32),
                         batch_size=batch_size, shuffle=shuffle)


class LibSVMIter(DataIter):
    """Sparse libsvm-format iterator yielding CSR batches
    (reference: `src/io/iter_libsvm.cc`)."""

    def __init__(self, data_libsvm, data_shape, batch_size, label_libsvm=None,
                 label_shape=None, round_batch=True, num_parts=1,
                 part_index=0, **kwargs):
        from ..base import part_range
        super().__init__(batch_size)
        self._num_features = int(data_shape[0] if isinstance(
            data_shape, (tuple, list)) else data_shape)
        self._labels, self._rows = self._parse(data_libsvm)
        lo, hi = part_range(len(self._rows), num_parts, part_index)
        self._labels, self._rows = self._labels[lo:hi], self._rows[lo:hi]
        self._cursor = 0

    def _parse(self, path):
        labels, rows = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                rows.append([(int(p.split(":")[0]), float(p.split(":")[1]))
                             for p in parts[1:]])
        return np.asarray(labels, np.float32), rows

    def reset(self):
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_features))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def next(self):
        from ..ndarray.sparse import CSRNDArray
        import jax.numpy as jnp
        if self._cursor >= len(self._rows):
            raise StopIteration
        rows = self._rows[self._cursor:self._cursor + self.batch_size]
        labels = list(self._labels[self._cursor:self._cursor + self.batch_size])
        self._cursor += self.batch_size
        pad = self.batch_size - len(rows)
        while len(rows) < self.batch_size:  # wrap-around padding (round_batch)
            take = min(self.batch_size - len(rows), len(self._rows))
            rows = rows + self._rows[:take]
            labels.extend(self._labels[:take])
        labels = np.asarray(labels, np.float32)
        values, indices, indptr = [], [], [0]
        for r in rows:
            for idx, val in r:
                indices.append(idx)
                values.append(val)
            indptr.append(len(values))
        data = CSRNDArray(
            jnp.asarray(np.asarray(values, np.float32)),
            jnp.asarray(np.asarray(indices, np.int32)),
            jnp.asarray(np.asarray(indptr, np.int32)),
            (len(rows), self._num_features))
        return DataBatch([data], [_nd.array(labels)], pad=pad)


class CSVIter(DataIter):
    """Reference: `src/io/iter_csv.cc`."""

    def __init__(self, data_csv, data_shape, batch_size, label_csv=None,
                 label_shape=(1,), round_batch=True, num_parts=1,
                 part_index=0, **kwargs):
        from ..base import part_range
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32) \
            if label_csv else np.zeros(len(data), np.float32)
        lo, hi = part_range(len(data), num_parts, part_index)
        self._inner = NDArrayIter(data[lo:hi], label[lo:hi],
                                  batch_size=batch_size)

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label
