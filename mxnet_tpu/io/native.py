"""ctypes bindings for the native C++ data pipeline (native/
recordio_pipeline.cc — the equivalent of the reference's C++
`src/io/iter_image_recordio_2.cc` decode/augment/prefetch stack).

Loads `native/libmxtpu_io.so`, building it with `make` on first use when a
toolchain is present. All entry points degrade gracefully: callers check
`available()` and fall back to the Python thread-pool path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "NativeImagePipeline"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libmxtpu_io.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False

# Must match mxtpu_abi_version() in recordio_pipeline.cc.  A stale prebuilt
# .so loads fine under ctypes and silently IGNORES trailing args added since
# it was built (num_parts/part_index would read the full record set on every
# worker — duplicated epochs, no error), so version skew must hard-fail.
_ABI_VERSION = 2


def _load():
    global _lib, _load_failed
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        # Always run make: mtime-aware, a cheap no-op when the .so is
        # current, and the only thing that rebuilds a STALE prebuilt binary
        # (os.path.exists alone let one load forever).  An fcntl lock
        # serializes concurrent cold loads (launch.py workers): g++ links
        # in place, so a peer must not dlopen a half-written .so.
        try:
            import fcntl
            lock_f = open(os.path.join(_NATIVE_DIR, ".build.lock"), "w")
            fcntl.flock(lock_f, fcntl.LOCK_EX)
        except Exception:
            lock_f = None
        try:
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "libmxtpu_io.so"],
                               capture_output=True, check=True, timeout=120)
            except Exception:
                if not os.path.exists(_SO_PATH):
                    _load_failed = True
                    return None
                # no toolchain but a .so exists — the ABI check below decides
            try:
                lib = ctypes.CDLL(_SO_PATH)
            except OSError:
                _load_failed = True
                return None
        finally:
            if lock_f is not None:
                lock_f.close()  # releases the flock
        try:
            got = int(lib.mxtpu_abi_version())
        except AttributeError:
            got = 0  # pre-versioning binary: definitely stale
        if got != _ABI_VERSION:
            # set BEFORE warning: under -W error the warn raises, and the
            # failure must stay cached (and available() must not explode)
            _load_failed = True
            import warnings
            try:
                warnings.warn(
                    "native/libmxtpu_io.so ABI v%d != expected v%d (stale "
                    "build?); refusing to load — run `make -C native clean "
                    "all`" % (got, _ABI_VERSION), RuntimeWarning)
            except RuntimeWarning:
                pass
            return None
        lib.mxtpu_pipe_create.restype = ctypes.c_void_p
        lib.mxtpu_pipe_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.mxtpu_pipe_next.restype = ctypes.c_int
        lib.mxtpu_pipe_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float)]
        lib.mxtpu_pipe_num_batches.restype = ctypes.c_int
        lib.mxtpu_pipe_num_batches.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_num_samples.restype = ctypes.c_int
        lib.mxtpu_pipe_num_samples.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_decode_failures.restype = ctypes.c_int
        lib.mxtpu_pipe_decode_failures.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_reset.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_destroy.argtypes = [ctypes.c_void_p]
        lib.mxtpu_last_error.restype = ctypes.c_char_p
        _lib = lib
        return _lib


def available():
    return _load() is not None


class NativeImagePipeline:
    """Owns one native pipeline handle; yields (data, label, pad) batches."""

    def __init__(self, rec_path, idx_path, batch_size, data_shape,
                 num_threads=4, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean=None, std=None, seed=0,
                 label_width=1, num_parts=1, part_index=0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native pipeline unavailable")
        self._lib = lib
        c, h, w = data_shape
        mean_arr = (ctypes.c_float * 3)(*(list(mean or [0, 0, 0])[:3]))
        std_arr = (ctypes.c_float * 3)(*(list(std or [1, 1, 1])[:3]))
        self._handle = lib.mxtpu_pipe_create(
            rec_path.encode(), (idx_path or "").encode(), batch_size, c, h, w,
            num_threads, int(shuffle), int(rand_crop), int(rand_mirror),
            mean_arr, std_arr, seed, label_width, int(num_parts),
            int(part_index))
        if not self._handle:
            raise RuntimeError("native pipeline create failed: %s"
                               % lib.mxtpu_last_error().decode())
        self.batch_size = batch_size
        self.data_shape = (c, h, w)
        self.label_width = label_width
        self._data_buf = np.empty((batch_size, c, h, w), np.float32)
        self._label_buf = np.empty((batch_size, label_width), np.float32)

    @property
    def num_batches(self):
        return self._lib.mxtpu_pipe_num_batches(self._handle)

    @property
    def num_samples(self):
        return self._lib.mxtpu_pipe_num_samples(self._handle)

    @property
    def decode_failures(self):
        return self._lib.mxtpu_pipe_decode_failures(self._handle)

    def next(self):
        """Returns (data NCHW f32, label f32, pad) or None at epoch end."""
        n = self._lib.mxtpu_pipe_next(
            self._handle,
            self._data_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._label_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n <= 0:
            if n < 0:
                raise RuntimeError("native pipeline error: %s"
                                   % self._lib.mxtpu_last_error().decode())
            return None
        # copy out: the ring slot behind the buffer is recycled immediately
        return (self._data_buf.copy(), self._label_buf.copy(),
                self.batch_size - n)

    def reset(self):
        self._lib.mxtpu_pipe_reset(self._handle)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.mxtpu_pipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
