"""RecordIO: the reference's packed-dataset container format.

Reference: `3rdparty/dmlc-core/include/dmlc/recordio.h` (magic-framed records)
and `python/mxnet/recordio.py` (MXRecordIO / IndexedRecordIO / IRHeader pack
format used by `tools/im2rec.py`). The binary layout is kept bit-compatible
so .rec packs made for the reference load here unchanged:

    [kMagic:u32][cflag<<29|len:u32][payload...][pad to 4B]

IRHeader: <IfQQ> = (flag, label, id, id2); flag>0 means `flag` float32 labels
follow the header.
"""
from __future__ import annotations

import io as _pyio
import os
import struct

import numpy as np

__all__ = ["MXRecordIO", "IndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "imdecode"]

_K_MAGIC = 0xCED7230A
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class IRHeader:
    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag=0, label=0.0, id=0, id2=0):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


def pack(header, s):
    """Serialize IRHeader + payload bytes (reference: mx.recordio.pack)."""
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)):
        label = np.asarray(label, dtype=np.float32)
        hdr = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
        return hdr + label.tobytes() + s
    hdr = struct.pack(_IR_FORMAT, 0, float(label), header.id, header.id2)
    return hdr + s


def unpack(s):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(payload[:flag * 4], dtype=np.float32)
        payload = payload[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, payload


def imdecode(img_bytes, flag=1):
    """Decode an encoded image to an HWC uint8 numpy array.

    The reference uses OpenCV (`src/io/image_io.cc`); this build decodes via
    Pillow when available, and also accepts raw .npy payloads (our im2rec
    fallback encoding for zero-dependency environments)."""
    if img_bytes[:6] == b"\x93NUMPY":
        return np.load(_pyio.BytesIO(img_bytes), allow_pickle=False)
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError(
            "JPEG/PNG decode needs Pillow; pack with .npy payloads instead") from e
    img = Image.open(_pyio.BytesIO(img_bytes))
    if flag == 1:
        img = img.convert("RGB")
    elif flag == 0:
        img = img.convert("L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def pack_img(header, img, quality=95, img_fmt=".npy"):
    """Encode an image array and pack it (reference: mx.recordio.pack_img)."""
    if img_fmt == ".npy":
        buf = _pyio.BytesIO()
        np.save(buf, np.asarray(img), allow_pickle=False)
        return pack(header, buf.getvalue())
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("JPEG encode needs Pillow; use img_fmt='.npy'") from e
    buf = _pyio.BytesIO()
    arr = np.asarray(img)
    Image.fromarray(arr.squeeze() if arr.shape[-1] == 1 else arr).save(
        buf, format="JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG",
        quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    header, payload = unpack(s)
    return header, imdecode(payload, iscolor)


class MXRecordIO:
    """Sequential .rec reader/writer (reference: mx.recordio.MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.open()

    def open(self):
        if self.flag == "w":
            self._fp = open(self.uri, "wb")
        elif self.flag == "r":
            self._fp = open(self.uri, "rb")
        else:
            raise ValueError("flag must be 'r' or 'w'")
        self.writable = self.flag == "w"

    def close(self):
        self._fp.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def reset(self):
        self._fp.seek(0)

    def tell(self):
        return self._fp.tell()

    def seek(self, pos):
        self._fp.seek(pos)

    def write(self, buf):
        assert self.writable
        length = len(buf)
        self._fp.write(struct.pack("<II", _K_MAGIC, length & ((1 << 29) - 1)))
        self._fp.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self._fp.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        hdr = self._fp.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _K_MAGIC:
            raise IOError(f"invalid RecordIO magic {magic:#x} in {self.uri}")
        length = lrec & ((1 << 29) - 1)
        buf = self._fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self._fp.read(pad)
        return buf


class IndexedRecordIO(MXRecordIO):
    """Random-access .rec via .idx sidecar (reference: IndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    key, pos = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if self.writable:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)
