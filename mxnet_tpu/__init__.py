"""mxnet_tpu — a TPU-native deep learning framework with MXNet's capabilities.

Brand-new design (not a port): jax/XLA is the execution engine, Pallas the
kernel language, GSPMD mesh sharding the distribution layer. The public
surface mirrors the reference framework (`python/mxnet/`) so reference users
find everything where they expect it: `nd`, `autograd`, `gluon`, `optimizer`,
`metric`, `io`, `kvstore`, `module`, `profiler`.
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import random
from . import config
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import attribute
from .attribute import AttrScope
from .debug import debug

__all__ = [
    "nd", "ndarray", "autograd", "random", "context", "attribute",
    "AttrScope", "Context", "cpu", "gpu", "tpu", "current_context",
    "num_gpus", "num_tpus", "MXNetError", "config", "debug",
]

# Subpackages filled in over the build; imported lazily to keep import light
# and to avoid hard failures while the surface is under construction.
_LAZY = {
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "init": ".initializer",
    "initializer": ".initializer",
    "metric": ".metric",
    "callback": ".callback",
    "io": ".io",
    "kv": ".kvstore",
    "kvstore": ".kvstore",
    "mod": ".module",
    "module": ".module",
    "sym": ".symbol",
    "symbol": ".symbol",
    "model": ".module",
    "mon": ".monitor",
    "monitor": ".monitor",
    "name": ".name",
    "runtime": ".runtime",
    "operator": ".operator",
    "profiler": ".profiler",
    "telemetry": ".telemetry",
    "diagnostics": ".diagnostics",
    "resilience": ".resilience",
    "memsafe": ".memsafe",
    "check": ".check",
    "guard": ".guard",
    "goodput": ".goodput",
    "scope": ".scope",
    "serve": ".serve",
    "pages": ".pages",
    "trace": ".trace",
    "inspect": ".inspect",
    "dataflow": ".dataflow",
    "parallel": ".parallel",
    "test_utils": ".test_utils",
    "lr_scheduler": ".lr_scheduler",
    "image": ".image",
    "contrib": ".contrib",
    "recordio": ".io.recordio",
    "rtc": ".rtc",
    "visualization": ".visualization",
    "viz": ".visualization",
    "engine": ".engine",
    "executor": ".symbol.executor",
    "registry": ".registry",
    "util": ".util",
}


def __getattr__(name):
    import importlib
    if name in _LAZY:
        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute '{name}'")
