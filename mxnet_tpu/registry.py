"""Registry helper factories (reference: python/mxnet/registry.py —
get_register_func / get_create_func over the dmlc registry; here over
`base.Registry`).

`create` accepts the reference's flexible specs: an instance (passed
through), a registered name, a (name, kwargs) dict, or name plus kwargs —
the pattern `mx.optimizer.create` and `mx.initializer` use.
"""
from __future__ import annotations

import json

from .base import Registry

__all__ = ["get_register_func", "get_create_func", "get_registry"]

_registries = {}


def get_registry(base_class, nickname=None):
    """The Registry for a base class. Bridges to the in-tree convention
    first — modules like `optimizer`/`initializer`/`metric` keep a
    module-level `_registry` next to their base class, and the reference's
    registry functions share exactly that store (so
    `get_create_func(mx.optimizer.Optimizer)("sgd")` finds SGD).  Falls
    back to one fresh Registry per base-class OBJECT (not name: two
    unrelated `Loss` classes must not share a namespace)."""
    import sys
    mod = sys.modules.get(getattr(base_class, "__module__", None))
    shared = getattr(mod, "_registry", None)
    if isinstance(shared, Registry):
        return shared
    if base_class not in _registries:
        _registries[base_class] = Registry(
            nickname or base_class.__name__.lower())
    return _registries[base_class]


def get_register_func(base_class, nickname=None):
    reg = get_registry(base_class, nickname)

    def register(klass, name=None):
        if not (isinstance(klass, type) and issubclass(klass, base_class)):
            raise TypeError(f"can only register subclasses of "
                            f"{base_class.__name__}")
        return reg.register(name or klass.__name__, klass)

    register.__doc__ = f"Register a {reg.kind} subclass."
    return register


def get_create_func(base_class, nickname=None):
    reg = get_registry(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            if len(args) > 1 or kwargs:
                raise ValueError("no extra arguments with an instance")
            return args[0]
        if args and isinstance(args[0], str):
            name, args = args[0], args[1:]
            try:                      # JSON spec like '{"type": {...}}'
                spec = json.loads(name)
            except ValueError:
                spec = None
            if isinstance(spec, dict) and len(spec) == 1:
                ((name, kwargs2),) = spec.items()
                if not isinstance(kwargs2, dict):
                    raise ValueError(
                        f"JSON {reg.kind} spec must map a name to a kwargs "
                        f"dict, got {kwargs2!r}")
                kwargs = {**kwargs2, **kwargs}
            return reg.get(name)(*args, **kwargs)
        raise ValueError(f"cannot create {reg.kind} from {args!r}")

    create.__doc__ = f"Create a {reg.kind} from a name/instance/JSON spec."
    return create
