"""Flash attention for TPU (Pallas).

Replaces the reference's fused attention ops
(`src/operator/contrib/transformer.cc` `_contrib_interleaved_matmul_selfatt_*`)
with a blockwise online-softmax kernel: O(L) memory instead of the L×L score
matrix, MXU-sized tiles, f32 accumulation over bf16 inputs.

Layout convention here: (batch, heads, seq, head_dim).

Forward is a Pallas kernel on TPU; backward is the standard flash residual
formulation (recompute P from saved LSE) expressed in jnp — XLA fuses it well
at BERT-scale sequence lengths. CPU test meshes use the pure-jnp reference so
the whole framework tests under `--xla_force_host_platform_device_count`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG = -1e30


def mha_reference(q, k, v, bias=None, causal=False, sm_scale=None):
    """Pure-XLA multi-head attention. q,k,v: (B, H, L, D); bias: (B, 1|H, 1|Lq, Lk)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias
    if causal:
        Lq, Lk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Lq)[:, None] + (Lk - Lq)
        col = jnp.arange(Lk)[None, :]
        s = jnp.where(col <= row, s, _NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# --------------------------------------------------------------------------
# pallas forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *,
                sm_scale, causal, block_q, block_k, kv_len):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (block_q, D)
    num_kb = kv_len // block_k
    q_len = pl.num_programs(1) * block_q
    causal_off = kv_len - q_len  # align last query with last key (as reference)
    if causal:
        hi = jax.lax.div((qi + 1) * block_q + causal_off + block_k - 1, block_k)
        hi = jnp.clip(hi, 1, num_kb)
    else:
        hi = num_kb

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (block_q, block_k)
        s = s + bias_ref[pl.ds(bh, 1), pl.ds(kb * block_k, block_k)]  # (1,bk)
        if causal:
            row = qi * block_q + causal_off + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


try:  # pallas import is deferred so CPU-only environments still import us
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _flash_fwd_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    biasr = jnp.broadcast_to(bias[:, None, :], (B, H, Lk)).reshape(B * H, Lk)
    grid = (B * H, Lq // block_q)
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_len=Lk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            # full-array spec: (1, Lk) blocks violate the (8,128) sublane rule
            pl.BlockSpec((B * H, Lk), lambda b, i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(qr, kr, vr, biasr)
    return out.reshape(B, H, Lq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, causal, sm_scale, block_q, block_k):
    return _flash_fwd_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k)


def _flash_fwd(q, k, v, bias, causal, sm_scale, block_q, block_k):
    out = _flash_fwd_pallas(q, k, v, bias, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, bias, out)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, bias, out = res
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    s = s + bias[:, None, None, :]
    if causal:
        row = jnp.arange(Lq)[:, None] + (Lk - Lq)
        col = jnp.arange(Lk)[None, :]
        s = jnp.where(col <= row, s, _NEG)
    lse = jax.scipy.special.logsumexp(s, axis=-1, keepdims=True)
    p = jnp.exp(s - lse)                                  # (B,H,Lq,Lk) f32
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v.astype(jnp.float32))
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(bias))


_flash.defvjp(_flash_fwd, _flash_bwd)


def _round_up(x, m):
    return (x + m - 1) // m * m


def flash_attention(q, k, v, mask=None, causal=False, sm_scale=None,
                    block_q=256, block_k=256):
    """Multi-head attention, flash-style.

    Args:
      q, k, v: (batch, heads, seq, head_dim). bf16 or f32.
      mask: optional (batch, kv_seq) — True/1 where attendable (padding mask).
      causal: apply causal masking.
    Returns (batch, heads, q_seq, head_dim), q.dtype.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]

    use_pallas = _HAS_PALLAS and jax.default_backend() == "tpu"
    if not use_pallas:
        bias = None
        if mask is not None:
            bias = jnp.where(mask.astype(bool), 0.0, _NEG)[:, None, None, :]
        return mha_reference(q, k, v, bias=bias, causal=causal, sm_scale=sm_scale)

    block_q = min(block_q, _round_up(Lq, 128))
    block_k = min(block_k, _round_up(Lk, 128))
    Lq_p, Lk_p = _round_up(Lq, block_q), _round_up(Lk, block_k)
    if mask is not None:
        bias = jnp.where(mask.astype(bool), 0.0, _NEG).astype(jnp.float32)
    else:
        bias = jnp.zeros((B, Lk), jnp.float32)
    if Lk_p != Lk:
        bias = jnp.pad(bias, ((0, 0), (0, Lk_p - Lk)), constant_values=_NEG)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, Lk_p - Lk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, Lk_p - Lk), (0, 0)))
    if Lq_p != Lq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Lq_p - Lq), (0, 0)))
    out = _flash(q, k, v, bias, causal, sm_scale, block_q, block_k)
    if Lq_p != Lq:
        out = out[:, :, :Lq]
    return out
