"""Flash attention for TPU (Pallas).

Replaces the reference's fused attention ops
(`src/operator/contrib/transformer.cc` `_contrib_interleaved_matmul_selfatt_*`)
with a blockwise online-softmax kernel: O(L) memory instead of the L×L score
matrix, MXU-sized tiles, f32 accumulation over bf16 inputs.

Layout convention here: (batch, heads, seq, head_dim).

Forward is a Pallas kernel that also emits the row-wise log-sum-exp
residual. Backward is selected by sequence length: below
`_PALLAS_BWD_MIN_LEN` XLA's fused L×L formulation (reusing the saved LSE)
is faster; at long context the blockwise Pallas dq/dkv kernels win on both
memory and bandwidth. CPU test meshes use the pure-jnp reference so the
whole framework tests under `--xla_force_host_platform_device_count`.

TPU layout note: row-vector arrays (LSE, delta, padding bias) are carried as
(rows, 8, L) with (1, 8, block) BlockSpecs — Mosaic requires the last two
block dims be (8k, 128k) or span the array, and a blocked spec (unlike a
full-array output spec with a constant index map) is also what keeps each
grid program's writes disjoint, which matters when the batch×head grid dim
is declared "parallel" and megacore TPUs split it across TensorCores.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

_NEG = -1e30


def mha_reference(q, k, v, bias=None, causal=False, sm_scale=None,
                  dropout=0.0, dropout_key=None):
    """Pure-XLA multi-head attention. q,k,v: (B, H, L, D); bias: (B, 1|H, 1|Lq, Lk).

    dropout is applied to the attention probabilities (inverted scaling),
    matching the reference's attention-dropout in
    `src/operator/contrib/transformer.cc` consumers (gluonnlp BERT)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias
    if causal:
        Lq, Lk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Lq)[:, None] + (Lk - Lq)
        col = jnp.arange(Lk)[None, :]
        s = jnp.where(col <= row, s, _NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), jnp.zeros((), p.dtype))
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# pallas binds LAZILY at first use (mx.kernels hygiene: this module is
# reachable from hot paths via the pallas_ops package, and a kernels=off
# / CPU process must keep jax.experimental.pallas out of sys.modules —
# ci/run.sh sanity asserts it). `has_pallas()` resolves the import once;
# the legacy `_HAS_PALLAS` module global keeps its meaning after that.
pl = None
pltpu = None
_CompilerParams = None
_HAS_PALLAS = None


def has_pallas():
    """Resolve (once) whether pallas imports here. Replaces the old
    import-time `_HAS_PALLAS` probe; callers that read the module global
    directly must call this first (ring_attention does)."""
    global pl, pltpu, _CompilerParams, _HAS_PALLAS
    if _HAS_PALLAS is None:
        try:
            from jax.experimental import pallas as _pl
            from jax.experimental.pallas import tpu as _pltpu
            pl, pltpu = _pl, _pltpu
            # jax 0.4.x spells it TPUCompilerParams; newer jax renamed
            # it to CompilerParams. A module-LOCAL alias keeps the
            # kernels on the new name without mutating jax's namespace
            # (other libraries in the same process may feature-detect
            # the rename via hasattr).
            _CompilerParams = getattr(_pltpu, "CompilerParams", None) \
                or _pltpu.TPUCompilerParams
            _HAS_PALLAS = True
        except Exception:  # pragma: no cover
            _HAS_PALLAS = False
    return _HAS_PALLAS


def _interpret():
    """MXNET_TPU_PALLAS_INTERPRET=1 runs the kernels through the Pallas
    interpreter on any backend — the only way the kernel CODE (not the jnp
    fallback) gets exercised off-TPU, used by
    tests/unittest/test_flash_interpret.py."""
    import os
    return os.environ.get("MXNET_TPU_PALLAS_INTERPRET", "0") == "1"


# --------------------------------------------------------------------------
# shared block math — the ONE definition of the masked score tile, used by
# forward and both backward kernels so fwd/bwd can never drift apart
# --------------------------------------------------------------------------

def _score_block(q, k, bias_row, qi, kb, causal, causal_off, block_q,
                 block_k, sm_scale):
    """Scaled masked scores for one (q block, k block) tile.

    q (block_q, D), k (block_k, D) in the MODEL dtype — bf16 operands hit
    the MXU's native bf16 x bf16 -> f32 mode; upcasting them first would
    force the (4x slower) f32 systolic path. bias_row (1, block_k) f32
    additive. Returns s (block_q, block_k) f32.
    """
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    s = s + bias_row
    if causal:
        row = qi * block_q + causal_off + \
            jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        col = kb * block_k + \
            jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(col <= row, s, _NEG)
    return s


def _keep_tile(seed_ref, b, qi, kb, num_qb, num_kb, block_q, block_k, dropout):
    """Attention-dropout keep mask for score tile (b, qi, kb).

    The per-core PRNG is re-seeded from (step seed, flat tile id) before
    every tile, so the forward, dq, and dkv kernels regenerate bit-identical
    masks regardless of their different grid/loop iteration orders. Mosaic
    caps prng_seed at two values, hence the flat id."""
    tile = (b * num_qb + qi) * num_kb + kb
    pltpu.prng_seed(seed_ref[0], tile)
    bits = pltpu.bitcast(pltpu.prng_random_bits((block_q, block_k)),
                         jnp.uint32)
    cutoff = np.uint32(min(int(round(dropout * 2.0 ** 32)), 0xFFFFFFFF))
    return bits >= cutoff


# --------------------------------------------------------------------------
# pallas forward (emits out + row LSE)
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, lse_ref, *,
                sm_scale, causal, block_q, block_k, kv_len, dropout):
    qi = pl.program_id(1)
    q = q_ref[0]                                         # (block_q, D)
    num_kb = kv_len // block_k
    q_len = pl.num_programs(1) * block_q
    causal_off = kv_len - q_len  # align last query with last key (as reference)
    if causal:
        hi = jax.lax.div((qi + 1) * block_q + causal_off + block_k - 1, block_k)
        hi = jnp.clip(hi, 1, num_kb)
    else:
        hi = num_kb

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        bias_row = bias_ref[0, 0, pl.ds(kb * block_k, block_k)] \
            .reshape(1, block_k)
        s = _score_block(q, k, bias_row, qi, kb, causal, causal_off,
                         block_q, block_k, sm_scale)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # l (the softmax denominator) sums the UNDROPPED p; dropout only
        # thins what reaches the value accumulation
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            keep = _keep_tile(seed_ref, pl.program_id(0), qi, kb,
                              pl.num_programs(1), num_kb, block_q, block_k,
                              dropout)
            p = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout))
        # p rounds to the model dtype for the value matmul: bf16 x bf16 ->
        # f32-accumulate is the MXU's full-rate mode, and p in [0, 1/keep]
        # loses ~3 mantissa-decimal at bf16 — the standard flash trade
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # log-sum-exp residual, broadcast over the 8-sublane carrier dim
    lse = (m + jnp.log(l)).reshape(1, 1, block_q)
    lse_ref[...] = jnp.broadcast_to(lse, (1, 8, block_q))


def _row8(x):
    """(R, L) -> (R, 8, L): 8-sublane carrier layout (see module docstring)."""
    return jnp.broadcast_to(x[:, None, :], (x.shape[0], 8, x.shape[1]))


def _flash_fwd_pallas(q, k, v, bias, seed, causal, sm_scale, block_q, block_k,
                      dropout):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    bias8 = _row8(bias)                                   # (B, 8, Lk)
    grid = (B * H, Lq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_len=Lk, dropout=dropout),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 8, Lk), lambda b, i, H=H: (b // H, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 8, Lq), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(qr, kr, vr, bias8, seed)
    return out.reshape(B, H, Lq, D), lse


# --------------------------------------------------------------------------
# pallas backward: dq kernel (grid over q blocks) + dkv kernel (over k blocks)
# --------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
               seed_ref, dq_ref, *, sm_scale, causal, block_q, block_k,
               kv_len, dropout):
    qi = pl.program_id(1)
    q = q_ref[0]
    g = g_ref[0]
    lse_c = lse_ref[0, 0, :].reshape(block_q, 1)
    delta_c = delta_ref[0, 0, :].reshape(block_q, 1)
    num_kb = kv_len // block_k
    q_len = pl.num_programs(1) * block_q
    causal_off = kv_len - q_len
    if causal:
        hi = jax.lax.div((qi + 1) * block_q + causal_off + block_k - 1,
                         block_k)
        hi = jnp.clip(hi, 1, num_kb)
    else:
        hi = num_kb

    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)

    def body(kb, acc):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        bias_row = bias_ref[0, 0, pl.ds(kb * block_k, block_k)] \
            .reshape(1, block_k)
        s = _score_block(q, k, bias_row, qi, kb, causal, causal_off,
                         block_q, block_k, sm_scale)
        p = jnp.exp(s - lse_c)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout > 0.0:
            keep = _keep_tile(seed_ref, pl.program_id(0), qi, kb,
                              pl.num_programs(1), num_kb, block_q, block_k,
                              dropout)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout))
        ds = (p * (dp - delta_c) * sm_scale).astype(k.dtype)
        return acc + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    dq_ref[0] = jax.lax.fori_loop(0, hi, body, acc0).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
                seed_ref, dk_ref, dv_ref, *, sm_scale, causal, block_q,
                block_k, q_len, kv_len, dropout):
    kb = pl.program_id(1)
    k = k_ref[0]                                           # (block_k, D)
    v = v_ref[0]
    bias_row = bias_ref[0, 0, pl.ds(kb * block_k, block_k)] \
        .reshape(1, block_k)
    num_qb = q_len // block_q
    causal_off = kv_len - q_len
    if causal:
        lo = jax.lax.div(kb * block_k - causal_off, block_q)
        lo = jnp.clip(lo, 0, num_qb)
    else:
        lo = 0

    dk0 = jnp.zeros((k.shape[0], k.shape[1]), jnp.float32)
    dv0 = jnp.zeros((v.shape[0], v.shape[1]), jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :]
        g = g_ref[0, pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)].reshape(block_q, 1)
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)] \
            .reshape(block_q, 1)
        s = _score_block(q, k, bias_row, qi, kb, causal, causal_off,
                         block_q, block_k, sm_scale)
        p = jnp.exp(s - lse)                               # (bq, bk)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        pv = p
        if dropout > 0.0:
            keep = _keep_tile(seed_ref, pl.program_id(0), qi, kb,
                              q_len // block_q, pl.num_programs(1),
                              block_q, block_k, dropout)
            inv = 1.0 / (1.0 - dropout)
            pv = jnp.where(keep, p, 0.0) * inv
            dp = jnp.where(keep, dp, 0.0) * inv
        dv = dv + jax.lax.dot_general(pv.astype(g.dtype), g,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(lo, num_qb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, bias, seed, out, lse, g, causal, sm_scale,
                      block_q, block_k, dropout):
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    qr = q.reshape(B * H, Lq, D)
    kr = k.reshape(B * H, Lk, D)
    vr = v.reshape(B * H, Lk, D)
    gr = g.reshape(B * H, Lq, D)
    bias8 = _row8(bias)                                    # (B, 8, Lk)
    # delta = rowsum(dO * O): one fused elementwise+reduce, no L×L tensor
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(B * H, Lq)
    delta8 = _row8(delta)                                  # (BH, 8, Lq)
    # lse already arrives in (BH, 8, Lq) carrier layout from the forward

    bias_spec = pl.BlockSpec((1, 8, Lk), lambda b, i, H=H: (b // H, 0, 0))
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=Lk,
                          dropout=dropout),
        grid=(B * H, Lq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Lk, D), lambda b, i: (b, 0, 0)),
            bias_spec,
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
            seed_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lq, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(qr, kr, vr, bias8, gr, lse, delta8, seed)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          q_len=Lq, kv_len=Lk, dropout=dropout),
        grid=(B * H, Lk // block_k),
        in_specs=[
            pl.BlockSpec((1, Lq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            bias_spec,
            pl.BlockSpec((1, Lq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 8, Lq), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 8, Lq), lambda b, j: (b, 0, 0)),
            seed_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Lk, D), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(qr, kr, vr, bias8, gr, lse, delta8, seed)

    return (dq.reshape(B, H, Lq, D), dk.reshape(B, H, Lk, D),
            dv.reshape(B, H, Lk, D))


def _flash_bwd_xla(q, k, v, bias, out, lse, g, causal, sm_scale):
    """Materialized backward, reusing the saved LSE (same score convention
    as `_score_block`, whole-matrix form). At short sequence lengths XLA's
    fused L×L formulation beats the blockwise kernels; the Pallas path
    exists for the long-context regime where the L×L buffer is the
    problem."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    s = s + bias[:, None, None, :]
    if causal:
        row = jnp.arange(Lq)[:, None] + (Lk - Lq)
        col = jnp.arange(Lk)[None, :]
        s = jnp.where(col <= row, s, _NEG)
    lse_rows = lse[:, 0, :].reshape(B, H, Lq, 1)
    p = jnp.exp(s - lse_rows)
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v.astype(jnp.float32))
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# Above this many kv positions the blockwise Pallas backward wins; below it
# XLA's fused L×L backward is faster. Measured with 512x512 blocks at
# BERT-base shapes: Pallas fwd+bwd 5.3ms vs Pallas-fwd+XLA-bwd 6.6ms at
# L=512, 1.47x at L=4096 — so the crossover sits at 512. With attention
# dropout the Pallas backward is used at every length: only it can
# regenerate the kernel-PRNG masks.
# Knob: config 'pallas_bwd_min_len' / MXNET_TPU_PALLAS_BWD_MIN_LEN.


def _pallas_bwd_min_len():
    from .. import config
    return config.get("pallas_bwd_min_len")


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, bias, seed, causal, sm_scale, block_q, block_k, dropout):
    out, _ = _flash_fwd_pallas(q, k, v, bias, seed, causal, sm_scale,
                               block_q, block_k, dropout)
    return out


def _flash_fwd(q, k, v, bias, seed, causal, sm_scale, block_q, block_k,
               dropout):
    out, lse = _flash_fwd_pallas(q, k, v, bias, seed, causal, sm_scale,
                                 block_q, block_k, dropout)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, dropout, res, g):
    q, k, v, bias, seed, out, lse = res
    if dropout > 0.0 or k.shape[2] >= _pallas_bwd_min_len():
        dq, dk, dv = _flash_bwd_pallas(q, k, v, bias, seed, out, lse, g,
                                       causal, sm_scale, block_q, block_k,
                                       dropout)
    else:
        dq, dk, dv = _flash_bwd_xla(q, k, v, bias, out, lse, g, causal,
                                    sm_scale)
    return (dq, dk, dv, jnp.zeros_like(bias),
            np.zeros(seed.shape, jax.dtypes.float0))


_flash.defvjp(_flash_fwd, _flash_bwd)


def _round_up(x, m):
    return (x + m - 1) // m * m


def _fit_block(b, L):
    """Largest 128-multiple <= b that divides the lane-padded length, so a
    big default block never forces padding beyond round_up(L, 128) (e.g.
    L=768 runs at 384 blocks unpadded instead of padding to 1024).
    Arbitrary caller values are clamped into the 128-multiple grid first;
    128 always divides Lp, so the loop terminates."""
    Lp = _round_up(L, 128)
    b = max(128, min(b, Lp) // 128 * 128)
    while Lp % b:
        b -= 128
    return b


def flash_attention(q, k, v, mask=None, causal=False, sm_scale=None,
                    block_q=512, block_k=512, dropout=0.0, dropout_key=None):
    """Multi-head attention, flash-style.

    Args:
      q, k, v: (batch, heads, seq, head_dim). bf16 or f32.
      mask: optional (batch, kv_seq) — True/1 where attendable (padding mask).
      causal: apply causal masking.
      dropout: attention-probability dropout rate (training). Requires
        dropout_key (a jax PRNG key); silently 0 when the key is absent so
        inference code never pays for RNG plumbing.
    Returns (batch, heads, q_seq, head_dim), q.dtype.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if dropout_key is None:
        dropout = 0.0
    B, H, Lq, D = q.shape
    Lk = k.shape[2]

    # backend test FIRST: a CPU backend without the interpreter never
    # triggers the pallas import at all (mx.kernels hygiene)
    use_pallas = (jax.default_backend() == "tpu" or _interpret()) \
        and has_pallas()
    if not use_pallas:
        bias = None
        if mask is not None:
            bias = jnp.where(mask.astype(bool), 0.0, _NEG)[:, None, None, :]
        return mha_reference(q, k, v, bias=bias, causal=causal,
                             sm_scale=sm_scale, dropout=dropout,
                             dropout_key=dropout_key)

    block_q = _fit_block(block_q, Lq)
    block_k = _fit_block(block_k, Lk)
    Lq_p, Lk_p = _round_up(Lq, block_q), _round_up(Lk, block_k)
    if mask is not None:
        bias = jnp.where(mask.astype(bool), 0.0, _NEG).astype(jnp.float32)
    else:
        bias = jnp.zeros((B, Lk), jnp.float32)
    if Lk_p != Lk:
        bias = jnp.pad(bias, ((0, 0), (0, Lk_p - Lk)), constant_values=_NEG)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, Lk_p - Lk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, Lk_p - Lk), (0, 0)))
    if Lq_p != Lq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Lq_p - Lq), (0, 0)))
    if dropout > 0.0:
        seed = jax.lax.bitcast_convert_type(
            jax.random.bits(dropout_key, (1,), jnp.uint32), jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    out = _flash(q, k, v, bias, seed, causal, sm_scale, block_q, block_k,
                 float(dropout))
    if Lq_p != Lq:
        out = out[:, :, :Lq]
    return out
