"""Fused MoE dispatch/combine: gather-by-expert + scatter-back with
capacity masking, without the (N, E, C) one-hot tensor.

`parallel/moe.py`'s dense-dispatch formulation materializes a
(tokens, experts, capacity) float dispatch tensor in HBM and einsums
against it twice — O(N*E*C) memory traffic for what is logically a
permutation. mx.inspect's roofline classifies those einsums
memory-bound. These kernels keep the selection one-hot in VMEM, built
on the fly from compact (N,) routing vectors via iota compares, and
express the gather/scatter as MXU matmuls per expert tile:

  dispatch:  buf[e, c]  = sum_n [expert_n == e][pos_n == c] * x[n]
  combine :  y[n]       = gate_n * buf[expert_n, pos_n]

HBM traffic drops from O(N*E*C + N*D + E*C*D) to O(N*D + E*C*D); the
(C, n_block) selection tile lives and dies in VMEM.

Both ops are differentiable where the training path needs them —
dispatch in x, combine in (buf, gate) — and the VJPs are each other:
d(dispatch)/dx is a combine with unit gate; d(combine)/dbuf is a
dispatch of the gate-scaled cotangent. The routing ints carry
`float0` tangents (the flash-attention seed convention).

These run INSIDE `shard_map` (per-device manual code), so unlike the
fused-update kernels they engage on any mesh. Fallback
(`kernels=off` / no TPU / no interpreter): the same one-hot einsum
formulation moe.py always used — bit-identical.

Routing convention: `expert` (N,) int32 in [0, E); `pos` (N,) int32 is
the token's slot within its expert's capacity buffer, with OVERFLOW AND
INVALID TOKENS CARRYING pos >= capacity or pos < 0 (they dispatch
nowhere and combine to zero — the Switch-style capacity drop).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from . import _common

__all__ = ["dispatch_to_experts", "combine_from_experts",
           "dispatch_reference", "combine_reference", "engaged"]

_LANE = 128


def engaged():
    """Trace-time gate (shard_map-safe: no device-count restriction)."""
    return _common.use_pallas()


# --------------------------------------------------------------------------
# references (the pre-kernel einsum formulation, and the VJP oracle)
# --------------------------------------------------------------------------

def _one_hot_dispatch(expert, pos, num_experts, capacity):
    """(N, E, C) f32 selection tensor from compact routing — exactly the
    `dispatch` moe.moe_dispatch builds (pos >= capacity or < 0 drops)."""
    e_oh = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)
    valid = (pos >= 0) & (pos < capacity)
    p_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                          dtype=jnp.float32)
    return e_oh[:, :, None] * p_oh[:, None, :] \
        * valid[:, None, None].astype(jnp.float32)


def dispatch_reference(x, expert, pos, num_experts, capacity):
    d = _one_hot_dispatch(expert, pos, num_experts, capacity)
    return jnp.einsum("nec,nd->ecd", d, x.astype(jnp.float32))


def combine_reference(buf, expert, pos, gate):
    E, C, _ = buf.shape
    d = _one_hot_dispatch(expert, pos, E, C) * gate[:, None, None]
    return jnp.einsum("nec,ecd->nd", d, buf)


# --------------------------------------------------------------------------
# kernels
# --------------------------------------------------------------------------

_row8 = _common.row8
_round_up = _common.round_up


def _dispatch_kernel(x_ref, exp_ref, pos_ref, buf_ref, *, block_n, n_nb,
                     capacity):
    """Grid over experts: program e accumulates its (C, D) buffer as
    sel(C, block_n) @ x(block_n, D) over token blocks — the selection
    tile is built in VMEM from iota compares, never written to HBM."""
    e = pl.program_id(0)
    C = buf_ref.shape[1]
    D = x_ref.shape[1]
    acc0 = jnp.zeros((C, D), jnp.float32)

    def body(nb, acc):
        xs = x_ref[pl.ds(nb * block_n, block_n), :]
        er = exp_ref[0:1, pl.ds(nb * block_n, block_n)]       # (1, bn)
        pr = pos_ref[0:1, pl.ds(nb * block_n, block_n)]
        c_iota = jax.lax.broadcasted_iota(jnp.int32, (C, block_n), 0)
        sel = ((er == e) & (pr == c_iota)
               & (pr >= 0) & (pr < capacity)).astype(jnp.float32)
        return acc + jax.lax.dot_general(
            sel, xs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    buf_ref[0] = jax.lax.fori_loop(0, n_nb, body, acc0)


def _combine_kernel(buf_ref, exp_ref, pos_ref, gate_ref, y_ref, *,
                    num_experts, capacity):
    """Grid over token blocks: program i gathers its (block_n, D) rows
    as sel(block_n, C) @ buf[e](C, D) summed over experts, then scales
    by the gate column."""
    i = pl.program_id(0)
    bn = y_ref.shape[0]
    D = y_ref.shape[1]
    C = buf_ref.shape[1]
    er = exp_ref[0:1, pl.ds(i * bn, bn)]                      # (1, bn)
    pr = pos_ref[0:1, pl.ds(i * bn, bn)]
    gr = gate_ref[0:1, pl.ds(i * bn, bn)]
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, C), 1)
    pcol = pr.reshape(bn, 1)
    ecol = er.reshape(bn, 1)
    valid = (pcol >= 0) & (pcol < capacity)

    def body(e, acc):
        sel = ((ecol == e) & (pcol == c_iota) & valid).astype(jnp.float32)
        be = buf_ref[pl.ds(e, 1)][0]                          # (C, D)
        return acc + jax.lax.dot_general(
            sel, be, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(0, num_experts, body,
                            jnp.zeros((bn, D), jnp.float32))
    y_ref[...] = acc * gr.reshape(bn, 1)


def _pad_tokens(x, expert, pos, gate=None):
    """Pad the token dim to a lane multiple; padding tokens route
    nowhere (expert -1, pos -1)."""
    N = x.shape[0]
    Np = _round_up(max(N, _LANE), _LANE)
    if Np != N:
        x = jnp.pad(x, ((0, Np - N), (0, 0)))
        expert = jnp.pad(expert, (0, Np - N), constant_values=-1)
        pos = jnp.pad(pos, (0, Np - N), constant_values=-1)
        if gate is not None:
            gate = jnp.pad(gate, (0, Np - N))
    return x, expert, pos, gate, N, Np


def _dispatch_pallas(x, expert, pos, num_experts, capacity):
    _load_pallas()
    x = x.astype(jnp.float32)
    x, expert, pos, _, N, Np = _pad_tokens(x, expert, pos)
    D = x.shape[1]
    Dp = _round_up(D, _LANE)
    Cp = _round_up(capacity, 8)
    if Dp != D:
        x = jnp.pad(x, ((0, 0), (0, Dp - D)))
    block_n = min(512, Np)
    while Np % block_n:
        block_n -= _LANE
    buf = pl.pallas_call(
        functools.partial(_dispatch_kernel, block_n=block_n,
                          n_nb=Np // block_n, capacity=capacity),
        grid=(num_experts,),
        in_specs=[
            pl.BlockSpec((Np, Dp), lambda e: (0, 0)),
            pl.BlockSpec((8, Np), lambda e: (0, 0)),
            pl.BlockSpec((8, Np), lambda e: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Cp, Dp), lambda e: (e, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_experts, Cp, Dp),
                                       jnp.float32),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=_common.interpret(),
    )(x, _row8(expert.astype(jnp.int32)), _row8(pos.astype(jnp.int32)))
    return buf[:, :capacity, :D]


def _combine_pallas(buf, expert, pos, gate):
    _load_pallas()
    E, C, D = buf.shape
    Cp = _round_up(C, 8)
    Dp = _round_up(D, _LANE)
    if (Cp, Dp) != (C, D):
        buf = jnp.pad(buf, ((0, 0), (0, Cp - C), (0, Dp - D)))
    xdummy = jnp.zeros((expert.shape[0], 1), jnp.float32)
    _, expert, pos, gate, N, Np = _pad_tokens(xdummy, expert, pos, gate)
    block_n = min(512, Np)
    while Np % block_n:
        block_n -= _LANE
    y = pl.pallas_call(
        functools.partial(_combine_kernel, num_experts=E, capacity=C),
        grid=(Np // block_n,),
        in_specs=[
            pl.BlockSpec((E, Cp, Dp), lambda i: (0, 0, 0)),
            pl.BlockSpec((8, Np), lambda i: (0, 0)),
            pl.BlockSpec((8, Np), lambda i: (0, 0)),
            pl.BlockSpec((8, Np), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, Dp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Np, Dp), jnp.float32),
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=_common.interpret(),
    )(buf.astype(jnp.float32), _row8(expert.astype(jnp.int32)),
      _row8(pos.astype(jnp.int32)), _row8(gate.astype(jnp.float32)))
    return y[:N, :D]


_compiler_params = _common.compiler_params


# --------------------------------------------------------------------------
# differentiable entry points
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _dispatch(x, expert, pos, num_experts, capacity):
    return _dispatch_pallas(x, expert, pos, num_experts, capacity)


def _dispatch_fwd(x, expert, pos, num_experts, capacity):
    return (_dispatch_pallas(x, expert, pos, num_experts, capacity),
            (expert, pos))


def _dispatch_bwd(num_experts, capacity, res, dbuf):
    expert, pos = res
    ones = jnp.ones(expert.shape, jnp.float32)
    dx = _combine_pallas(dbuf, expert, pos, ones)
    z = np.zeros(expert.shape, jax.dtypes.float0)
    return dx, z, np.zeros(pos.shape, jax.dtypes.float0)


_dispatch.defvjp(_dispatch_fwd, _dispatch_bwd)


@jax.custom_vjp
def _combine(buf, expert, pos, gate):
    return _combine_pallas(buf, expert, pos, gate)


def _combine_fwd(buf, expert, pos, gate):
    return _combine_pallas(buf, expert, pos, gate), (buf, expert, pos,
                                                     gate)


def _combine_bwd(res, dy):
    buf, expert, pos, gate = res
    E, C, _ = buf.shape
    dbuf = _dispatch_pallas(dy * gate[:, None], expert, pos, E, C)
    gathered = _combine_pallas(buf, expert, pos,
                               jnp.ones(gate.shape, jnp.float32))
    dgate = jnp.sum(dy * gathered, axis=-1)
    return (dbuf, np.zeros(expert.shape, jax.dtypes.float0),
            np.zeros(pos.shape, jax.dtypes.float0),
            dgate.astype(gate.dtype))


_combine.defvjp(_combine_fwd, _combine_bwd)


def dispatch_to_experts(x, expert, pos, num_experts, capacity):
    """Gather tokens into per-expert capacity buffers: (N, D) ->
    (E, C, D) f32. Differentiable in `x`; `expert`/`pos` are routing
    ints (see module docstring for the overflow convention). Falls back
    to the one-hot einsum under kernels=off / no TPU."""
    if engaged():
        return _dispatch(x, expert, pos, num_experts, capacity)
    return dispatch_reference(x, expert, pos, num_experts, capacity)


def combine_from_experts(buf, expert, pos, gate):
    """Scatter expert outputs back to token order, gate-weighted:
    (E, C, D) -> (N, D) f32. Differentiable in `buf` and `gate`;
    dropped tokens (pos outside capacity) combine to zero and pass
    through the residual upstream."""
    if engaged():
        return _combine(buf, expert, pos, gate)
    return combine_reference(buf, expert, pos, gate)


# pallas binds lazily at first kernel engagement (shared logic in
# _common): this module sits on the moe_ffn hot path, and with
# kernels=off it must not drag jax.experimental.pallas into the
# process (ci sanity asserts it)
pl = None


def _load_pallas():
    global pl
    pl = _common.load_pallas()
