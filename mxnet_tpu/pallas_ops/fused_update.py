"""Fused optimizer update: grad-scale + moment update + weight apply in
one VMEM pass.

The optimizer tail of a train step is a chain of elementwise HLOs
(scale, clip, two moment EMAs, rsqrt, the weight apply) over every
parameter — mx.inspect's roofline classifies it memory-bound: each HLO
XLA fails to fuse is another full HBM round-trip over state that is
read-once/write-once. These kernels do the whole update per (rows, 128)
tile while it sits in VMEM, with `input_output_aliases` so w/m/v update
in place (donation-safe — the mx.check lint on the traced form stays
quiet).

Two surfaces:
  * `adam_update` — Adam / AdamW (decoupled_wd) per-parameter update,
    wired into `parallel/functional_opt.FunctionalOptimizer`. The math
    is EXACTLY `ops.optimizer_ops.adam_update`/`adamw_update` (the
    fallback calls them, so `kernels=off` is bit-identical to main).
  * `lamb_pass1` / `lamb_pass2` — the two elementwise passes of
    `parallel/fused_lamb.FusedLamb.apply_flat` over the flat fp32
    master layout: pass 1 produces the new moments plus the per-row
    sums of squares the trust-ratio norms need; the tiny per-segment
    scatter + trust ratio stays in XLA (R elements); pass 2 applies the
    trust-scaled update. The two-kernel split IS apply_flat's
    optimization_barrier structure: the update temp is never written to
    HBM, it is recomputed in pass 2.

The per-shard math composes with mx.zero: the kernels see only a flat
(rows, lane) view, so applying them per flat shard is bit-exact against
the whole-vector application (pinned by test_kernels.py). Engagement is
trace-time only (`engaged()`): kernels=off|non-TPU runs the reference,
and multi-device SPMD steps keep the XLA lowering (`pl.pallas_call` has
no GSPMD rule — see pallas_ops/_common.py).

Not differentiable by design: optimizer updates run outside autodiff
(no gradient flows through a weight apply), so no custom_vjp is
defined.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _common
from ..ops import OPS as _OPS

__all__ = ["adam_update", "lamb_pass1", "lamb_pass2", "engaged",
           "adam_update_reference"]

_LANE = 128


def engaged(n_elements):
    """Trace-time gate for the fused-update kernels: the knob asks, the
    backend can, the buffer clears kernels_min_elements (kernel launch
    overhead beats one fused pass on tiny LayerNorm/bias state), and
    the step is not a multi-device SPMD program. The interpreter
    overrides the SPMD gate: interpreted kernels lower to ordinary XLA
    ops the partitioner handles, and the gate would otherwise leave the
    kernel CODE untested on the 8-device CPU test mesh."""
    return (int(n_elements) >= _common.min_elements()
            and _common.use_pallas()
            and (_common.interpret() or not _common.multi_device()))


# --------------------------------------------------------------------------
# Adam / AdamW
# --------------------------------------------------------------------------

def adam_update_reference(w, g, m, v, lr, beta1, beta2, epsilon, wd,
                          rescale_grad, clip_gradient, decoupled_wd=False,
                          eta=1.0):
    """The XLA-native lowering — literally the registered optimizer ops
    the functional path always used, so the fallback cannot drift."""
    if decoupled_wd:
        return _OPS["adamw_update"](
            w, g, m, v, lr, eta=eta, beta1=beta1, beta2=beta2,
            epsilon=epsilon, wd=wd, rescale_grad=rescale_grad,
            clip_gradient=clip_gradient)
    return _OPS["adam_update"](
        w, g, m, v, lr, beta1=beta1, beta2=beta2, epsilon=epsilon,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient)


def _adam_kernel(lr_ref, w_ref, g_ref, m_ref, v_ref, wo_ref, mo_ref,
                 vo_ref, *, beta1, beta2, epsilon, wd, rescale_grad,
                 clip_gradient, decoupled_wd, eta):
    """One (rows, 128) tile: the full Adam/AdamW update in VMEM."""
    w32 = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if not decoupled_wd:
        g = g + wd * w32                    # Adam: wd folds into the grad
    new_m = beta1 * m_ref[...].astype(jnp.float32) + (1 - beta1) * g
    new_v = beta2 * v_ref[...].astype(jnp.float32) + (1 - beta2) \
        * jnp.square(g)
    lr = lr_ref[0]
    step = lr * new_m / (jnp.sqrt(new_v) + epsilon)
    if decoupled_wd:                        # AdamW: wd decoupled, eta-scaled
        step = eta * (step + wd * w32)
    wo_ref[...] = (w32 - step).astype(wo_ref.dtype)
    mo_ref[...] = new_m.astype(mo_ref.dtype)
    vo_ref[...] = new_v.astype(vo_ref.dtype)


def _pad_rows(flat, rows_mult=16):
    """1-D -> (R, 128) with R padded to a sublane multiple (16 covers
    the bf16 min tile; f32's 8 divides it); returns (view, n, R). Zero
    padding is self-consistent: a zero w/g/m/v lane produces a zero
    update (epsilon keeps the rsqrt finite)."""
    n = flat.shape[0]
    per = _LANE * rows_mult
    np_ = (n + per - 1) // per * per
    if np_ != n:
        flat = jnp.pad(flat, (0, np_ - n))
    return flat.reshape(np_ // _LANE, _LANE), n, np_ // _LANE


def adam_update(w, g, m, v, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                decoupled_wd=False, eta=1.0):
    """Fused Adam/AdamW update; returns (new_w, new_m, new_v) with the
    input dtypes. Hyperparameters are trace-time constants (they key the
    step cache upstream); `lr` may be traced (the in-jit scheduler)."""
    if not engaged(w.size):
        return adam_update_reference(
            w, g, m, v, lr, beta1, beta2, epsilon, wd, rescale_grad,
            clip_gradient, decoupled_wd=decoupled_wd, eta=eta)

    _load_pallas()
    shape = w.shape
    w2, n, R = _pad_rows(w.reshape(-1))
    g2, _, _ = _pad_rows(g.reshape(-1))
    m2, _, _ = _pad_rows(m.reshape(-1))
    v2, _, _ = _pad_rows(v.reshape(-1))
    block_r = min(512, R)
    while R % block_r:
        block_r -= 16
    lr1 = jnp.asarray(lr, jnp.float32).reshape(1)

    row_spec = pl.BlockSpec((block_r, _LANE), lambda i: (i, 0))
    new_w, new_m, new_v = pl.pallas_call(
        functools.partial(
            _adam_kernel, beta1=float(beta1), beta2=float(beta2),
            epsilon=float(epsilon), wd=float(wd),
            rescale_grad=float(rescale_grad),
            clip_gradient=float(clip_gradient), decoupled_wd=decoupled_wd,
            eta=float(eta)),
        grid=(R // block_r,),
        in_specs=[pl.BlockSpec(memory_space=_smem()),
                  row_spec, row_spec, row_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((R, _LANE), w.dtype),
            jax.ShapeDtypeStruct((R, _LANE), m.dtype),
            jax.ShapeDtypeStruct((R, _LANE), v.dtype),
        ],
        input_output_aliases={1: 0, 3: 1, 4: 2},   # w/m/v update in place
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=_common.interpret(),
    )(lr1, w2, g2, m2, v2)
    return (new_w.reshape(-1)[:n].reshape(shape),
            new_m.reshape(-1)[:n].reshape(shape),
            new_v.reshape(-1)[:n].reshape(shape))


# --------------------------------------------------------------------------
# fused-LAMB passes (flat (rows, 512) master layout)
# --------------------------------------------------------------------------

def _lamb1_kernel(sc_ref, w_ref, g_ref, m_ref, v_ref, wd_ref, mo_ref,
                  vo_ref, rw_ref, ru_ref, *, beta1, beta2, epsilon,
                  rescale_grad, clip_gradient, bias_correction,
                  moments_f32):
    """Pass 1: moment EMA (+ the storage-dtype round-trip) and the
    per-row sums of squares feeding the trust-ratio norms. sc = (c1, c2)
    bias-correction denominators (traced: they depend on t)."""
    W = w_ref[...].astype(jnp.float32)
    G = g_ref[...].astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        G = jnp.clip(G, -clip_gradient, clip_gradient)
    new_m = beta1 * m_ref[...].astype(jnp.float32) + (1 - beta1) * G
    new_v = beta2 * v_ref[...].astype(jnp.float32) + (1 - beta2) \
        * jnp.square(G)
    if not moments_f32:
        # reduced-precision moment storage: round-trip through the
        # storage dtype BEFORE the norms (fused_lamb.py's invariant —
        # trust must see what is stored)
        new_m = new_m.astype(mo_ref.dtype).astype(jnp.float32)
        new_v = new_v.astype(vo_ref.dtype).astype(jnp.float32)
    m_hat, v_hat = new_m, new_v
    if bias_correction:
        m_hat = new_m / sc_ref[0]
        v_hat = new_v / sc_ref[1]
    upd = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd_ref[...] * W
    mo_ref[...] = new_m.astype(mo_ref.dtype)
    vo_ref[...] = new_v.astype(vo_ref.dtype)
    rw_ref[...] = jnp.sum(jnp.square(W), axis=1, keepdims=True)
    ru_ref[...] = jnp.sum(jnp.square(upd), axis=1, keepdims=True)


def _lamb2_kernel(sc_ref, w_ref, m_ref, v_ref, wd_ref, tr_ref, wo_ref, *,
                  beta1, beta2, epsilon, bias_correction):
    """Pass 2: recompute the update from the stored moments (the
    recompute IS apply_flat's optimization barrier — pure FLOPs traded
    for never writing the update temp to HBM) and apply the trust-scaled
    step. sc = (c1, c2, lr)."""
    W = w_ref[...].astype(jnp.float32)
    new_m = m_ref[...].astype(jnp.float32)
    new_v = v_ref[...].astype(jnp.float32)
    m_hat, v_hat = new_m, new_v
    if bias_correction:
        m_hat = new_m / sc_ref[0]
        v_hat = new_v / sc_ref[1]
    upd = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd_ref[...] * W
    wo_ref[...] = W - sc_ref[2] * tr_ref[...] * upd


def _lamb_specs(R, C, block_r):
    row = pl.BlockSpec((block_r, C), lambda i: (i, 0))
    col = pl.BlockSpec((block_r, 1), lambda i: (i, 0))
    return row, col


def _lamb_block(R):
    # 16-row granularity: the moment buffers may store bf16
    # (lamb_moments_dtype), whose min sublane tile is 16
    block_r = min(256, R)
    while R % block_r:
        block_r -= 16
    return block_r


def _pad_rc(x2, Rp):
    R = x2.shape[0]
    return jnp.pad(x2, ((0, Rp - R), (0, 0))) if Rp != R else x2


def lamb_pass1(W, G, m, v, wd_rows, c1, c2, *, beta1, beta2, epsilon,
               rescale_grad, clip_gradient, bias_correction,
               moments_dtype=jnp.float32):
    """Fused-LAMB pass 1 over the flat (R, 512) layout. Returns
    (new_m (Rp, C), new_v (Rp, C), rowsq_w (R,), rowsq_upd (R,)): the
    moments stay ROW-PADDED for `lamb_pass2` to consume as-is (slice
    their [:R] prefix only when keeping them); the row sums feed
    FusedLamb's per-segment scatter-add norms (kept in XLA: R elements,
    off the hot path). Caller guarantees `engaged(W.size)`."""
    _load_pallas()
    R, C = W.shape
    Rp = (R + 15) // 16 * 16
    block_r = _lamb_block(Rp)
    row, col = _lamb_specs(Rp, C, block_r)
    mdt = jnp.dtype(moments_dtype)
    sc = jnp.stack([jnp.asarray(c1, jnp.float32),
                    jnp.asarray(c2, jnp.float32)])
    new_m, new_v, rw, ru = pl.pallas_call(
        functools.partial(
            _lamb1_kernel, beta1=float(beta1), beta2=float(beta2),
            epsilon=float(epsilon), rescale_grad=float(rescale_grad),
            clip_gradient=(float(clip_gradient) if clip_gradient
                           else None),
            bias_correction=bool(bias_correction),
            moments_f32=mdt == jnp.float32),
        grid=(Rp // block_r,),
        in_specs=[pl.BlockSpec(memory_space=_smem()),
                  row, row, row, row, col],
        out_specs=[row, row, col, col],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, C), mdt),
            jax.ShapeDtypeStruct((Rp, C), mdt),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
        ],
        input_output_aliases={3: 0, 4: 1},          # moments update in place
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=_common.interpret(),
    )(sc, _pad_rc(W, Rp), _pad_rc(G, Rp),
      _pad_rc(m.reshape(R, C), Rp), _pad_rc(v.reshape(R, C), Rp),
      _pad_rc(wd_rows.reshape(R, 1), Rp))
    # moments return PADDED (Rp, C): pass 2 consumes them at the same
    # padding (its _pad_rc no-ops), so XLA never pays a pad(slice(x))
    # round-trip over the full moment buffers between passes — the
    # caller slices [:R] only on the values it keeps
    return (new_m, new_v, rw[:R, 0], ru[:R, 0])


def lamb_pass2(W, new_m, new_v, wd_rows, trust_rows, c1, c2, lr, *,
               beta1, beta2, epsilon, bias_correction):
    """Fused-LAMB pass 2: the trust-scaled weight apply. Returns the new
    flat (R, 512) f32 master."""
    _load_pallas()
    R, C = W.shape
    Rp = (R + 15) // 16 * 16
    block_r = _lamb_block(Rp)
    row, col = _lamb_specs(Rp, C, block_r)
    sc = jnp.stack([jnp.asarray(c1, jnp.float32),
                    jnp.asarray(c2, jnp.float32),
                    jnp.asarray(lr, jnp.float32)])
    mrow = pl.BlockSpec((block_r, C), lambda i: (i, 0))
    new_w = pl.pallas_call(
        functools.partial(
            _lamb2_kernel, beta1=float(beta1), beta2=float(beta2),
            epsilon=float(epsilon),
            bias_correction=bool(bias_correction)),
        grid=(Rp // block_r,),
        in_specs=[pl.BlockSpec(memory_space=_smem()),
                  row, mrow, mrow, col, col],
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((Rp, C), jnp.float32),
        input_output_aliases={1: 0},                # master updates in place
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=_common.interpret(),
    )(sc, _pad_rc(W, Rp), _pad_rc(new_m, Rp), _pad_rc(new_v, Rp),
      _pad_rc(wd_rows.reshape(R, 1), Rp),
      _pad_rc(trust_rows.reshape(R, 1), Rp))
    return new_w[:R]


_smem = _common.smem
_compiler_params = _common.compiler_params


# pallas binds lazily at first kernel engagement (shared logic in
# _common): this module sits on the optimizer hot path, and with
# kernels=off it must not drag jax.experimental.pallas into the
# process (ci sanity asserts it)
pl = None


def _load_pallas():
    global pl
    pl = _common.load_pallas()
