"""Pallas TPU kernels — the mx.kernels library.

The reference's hand-written CUDA/cuDNN kernels (SURVEY.md §2.1) map to
XLA codegen for almost everything; the exceptions live here as Pallas
kernels targeting the hot paths where mx.inspect's roofline says the
generic lowering loses (the TVM/Relay argument, PAPERS.md 1802.04799):

  * `flash_attention`     — blockwise online-softmax attention
  * `int8_matmul`         — int8 x int8 -> int32 serving matmul with the
                            per-channel rescale fused (QuantizedDense,
                            the mx.serve decode path)
  * `fused_update`        — one-VMEM-pass optimizer updates (Adam/AdamW
                            via FunctionalOptimizer; the fused-LAMB flat
                            master passes)
  * `moe_kernels`         — fused MoE dispatch/combine without the
                            (N, E, C) one-hot tensor (parallel/moe.py)
  * `paged_attention`     — one-token decode attention gathered through
                            an mx.pages block table (the paged serve
                            path), scalar-prefetch indexed so the dense
                            gathered operand never hits HBM

Every kernel sits behind the `kernels=off|auto|on` knob with a bit-exact
XLA-native fallback (see `pallas_ops/_common.py`), ships an
interpret-mode CPU path (MXNET_TPU_PALLAS_INTERPRET=1 — tier-1
exercises the kernel code, not just the reference), and is benchmarked
pallas-vs-XLA by `benchmarks/bench_kernels.py`. `tools/lint_rules.py`
forbids `pl.pallas_call` outside this package.

Import hygiene: every submodule defers its `jax.experimental.pallas`
import to first kernel ENGAGEMENT (backend probe first), so importing
this package — which the QuantizedDense / FunctionalOptimizer / moe_ffn
hot paths do — never drags pallas into a kernels=off or CPU process
(ci/run.sh sanity asserts sys.modules stays clean after a trainer step).
"""
from . import _common
from . import fused_update
from . import moe_kernels
# the function re-exports shadow the same-named submodules on the
# package, as they always have; the module spelling stays
# importlib.import_module (see tests/unittest/test_flash_interpret.py)
from .flash_attention import flash_attention, mha_reference
from .int8_matmul import int8_matmul, int8_matmul_reference
from .paged_attention import paged_attention, paged_attention_reference

__all__ = ["flash_attention", "mha_reference", "int8_matmul",
           "int8_matmul_reference", "paged_attention",
           "paged_attention_reference", "fused_update", "moe_kernels",
           "_common"]
