"""Pallas TPU kernels.

The reference's hand-written CUDA/cuDNN kernels (SURVEY.md §2.1) map to XLA
codegen for almost everything; the exceptions — attention (the reference's
`src/operator/contrib/transformer.cc` fused ops) — live here as Pallas
kernels, with a pure-jnp fallback for CPU test meshes.
"""
from .flash_attention import flash_attention, mha_reference

__all__ = ["flash_attention", "mha_reference"]
