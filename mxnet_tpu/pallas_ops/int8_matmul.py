"""Int8 serving matmul: int8 x int8 -> int32 on the MXU with the
per-channel rescale fused into the same kernel.

The quantized serving path (contrib/quantization.py QuantizedDense, the
mx.serve decode step) computes `dot(x_q, w_q) -> int32` followed by one
elementwise `acc * (x_scale * w_scale[o]) (+ bias) (relu)`. XLA lowers
that as matmul + a separate elementwise pass — an extra HBM round-trip
over the (M, O) accumulator, which is exactly what mx.inspect's roofline
flags on the memory-bound decode executables. This kernel keeps the
int32 accumulator in VMEM and applies scale/bias/relu before the single
write-back, and guarantees the int8 operands actually hit the MXU's
native int8 path (no silent dequantize-then-fp-matmul).

Fallback (`kernels=off`, non-TPU without the interpreter): the exact
XLA expression the quantized layers always used — bit-identical to a
build without this package. The op is not differentiable (integer
inputs); it exists for inference.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _common

__all__ = ["int8_matmul", "int8_matmul_reference"]


def int8_matmul_reference(x_q, w_q_t, x_scale, w_scale, bias=None,
                          relu=False):
    """The XLA-native lowering (the pre-kernel serving path, verbatim):
    int8 x int8 -> int32 `dot_general` (XLA maps it onto the MXU's int8
    mode on TPU), one rescale to f32, optional bias/relu."""
    acc = jax.lax.dot_general(
        x_q, w_q_t, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale * w_scale)
    if bias is not None:
        out = out + bias
    if relu:
        out = jnp.maximum(out, 0.0)
    return out


# --------------------------------------------------------------------------
# pallas kernel
# --------------------------------------------------------------------------

def _kernel(x_ref, w_ref, s_ref, b_ref, o_ref, *, block_k, n_kb, relu):
    """One (block_m, block_n) output tile: int32-accumulate over K in
    VMEM, then scale+bias+relu fused before the single f32 write-back.

    x (block_m, K) int8; w (K, block_n) int8; s/b (8, block_n) f32
    carriers (combined scale `x_scale * w_scale`, bias or zeros)."""
    acc0 = jnp.zeros((x_ref.shape[0], o_ref.shape[1]), jnp.int32)

    def body(kb, acc):
        xk = x_ref[:, pl.ds(kb * block_k, block_k)]
        wk = w_ref[pl.ds(kb * block_k, block_k), :]
        # int8 x int8 -> int32: the MXU's native low-precision path
        return acc + jax.lax.dot_general(
            xk, wk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    acc = jax.lax.fori_loop(0, n_kb, body, acc0)
    out = acc.astype(jnp.float32) * s_ref[0:1, :] + b_ref[0:1, :]
    if relu:
        out = jnp.maximum(out, 0.0)
    o_ref[...] = out


_round_up = _common.round_up
_row8 = _common.row8


def _int8_matmul_pallas(x_q, w_q_t, x_scale, w_scale, bias, relu):
    lead = x_q.shape[:-1]
    K = x_q.shape[-1]
    O = w_q_t.shape[1]
    M = 1
    for d in lead:
        M *= int(d)
    x2 = x_q.reshape(M, K)

    # pad every dim to the MXU grid; int8 operand tiles need 32-sublane
    # alignment, the f32 output tile 8 — 128 covers both lanes-wise
    Mp, Kp, Op = _round_up(M, 128), _round_up(K, 128), _round_up(O, 128)
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))
    if Kp != K:
        x2 = jnp.pad(x2, ((0, 0), (0, Kp - K)))
        w_q_t = jnp.pad(w_q_t, ((0, Kp - K), (0, 0)))
    if Op != O:
        w_q_t = jnp.pad(w_q_t, ((0, 0), (0, Op - O)))
    # the combined per-channel rescale: padding channels scale by 0 so
    # their (zero) accumulators stay zero through bias-less lanes
    s = (jnp.asarray(x_scale, jnp.float32)
         * w_scale.astype(jnp.float32)).reshape(-1)
    if s.shape[0] == 1 and O > 1:                   # per-tensor caller
        s = jnp.broadcast_to(s, (O,))
    b = jnp.zeros((O,), jnp.float32) if bias is None \
        else bias.astype(jnp.float32).reshape(-1)
    if Op != O:
        s = jnp.pad(s, (0, Op - O))
        b = jnp.pad(b, (0, Op - O))

    block_m = min(256, Mp)
    block_n = min(256, Op)
    block_k = min(512, Kp)
    while Mp % block_m:
        block_m -= 128
    while Op % block_n:
        block_n -= 128
    while Kp % block_k:
        block_k -= 128

    out = pl.pallas_call(
        functools.partial(_kernel, block_k=block_k, n_kb=Kp // block_k,
                          relu=relu),
        grid=(Mp // block_m, Op // block_n),
        in_specs=[
            pl.BlockSpec((block_m, Kp), lambda i, j: (i, 0)),
            pl.BlockSpec((Kp, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((8, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((8, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Op), jnp.float32),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=_common.interpret(),
    )(x2, w_q_t, _row8(s), _row8(b))
    return out[:M, :O].reshape(lead + (O,))


_compiler_params = _common.compiler_params


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def int8_matmul(x_q, w_q_t, x_scale, w_scale, bias=None, relu=False):
    """Quantized matmul with fused per-channel rescale.

    Args:
      x_q: (..., K) int8 activations (already quantized).
      w_q_t: (K, O) int8 weight, pre-transposed (QuantizedDense layout).
      x_scale: scalar f32 activation scale (traced or concrete).
      w_scale: (O,) f32 per-output-channel weight scales (a scalar /
        (1,) per-tensor scale is broadcast).
      bias: optional (O,) f32, fused into the kernel epilogue.
      relu: fuse a relu into the epilogue.

    Returns (..., O) f32. `kernels=off` (or no TPU/interpreter) runs
    `int8_matmul_reference` — bit-identical to the pre-kernel path.
    """
    if x_q.dtype != jnp.int8 or w_q_t.dtype != jnp.int8:
        raise TypeError(
            f"int8_matmul needs int8 operands, got {x_q.dtype} x "
            f"{w_q_t.dtype} (quantize first; the fp path is nn.Dense)")
    if _common.use_pallas():
        _load_pallas()
        return _int8_matmul_pallas(x_q, w_q_t, x_scale,
                                   jnp.asarray(w_scale, jnp.float32),
                                   bias, relu)
    return int8_matmul_reference(x_q, w_q_t, x_scale, w_scale,
                                 bias=bias, relu=relu)


# pallas binds lazily at first kernel engagement (shared logic in
# _common): this module sits on the QuantizedDense/serve hot path, and
# with kernels=off it must not drag jax.experimental.pallas into the
# process (ci sanity asserts it)
pl = None


def _load_pallas():
    global pl
    pl = _common.load_pallas()
