"""Paged decode attention: one-token attention over a block-table KV
cache (vLLM/PagedAttention, PAPERS.md 2309.06180).

mx.pages stores each sequence's K/V as a LIST of fixed-size pages in a
pooled (pages, H, page_size, D) array; a decode step must attend row b's
query over the positions <= t[b] scattered across its page table. XLA's
lowering of that gather (`k_pages[tables]` then a dense attention)
materializes the gathered (B, H, L, D) operand in HBM before the matmul
— an extra full-cache round-trip per token, on the executable mx.inspect
already flags memory-bound. This kernel walks the page table inside the
grid instead: scalar-prefetched block indices drive the BlockSpec
index_map, so each (batch, page) program DMAs exactly one page from the
pool into VMEM and accumulates online-softmax state — the gathered
operand never exists.

Fallback (`kernels=off`, non-TPU without the interpreter): the gather +
the EXACT dense per-row attention expression
(`models/_decode.batched_cached_attention_step`'s f32 score/softmax/PV
math) — when the page tables tile a contiguous [0, L) range this is
bit-identical to the dense slot cache path, which is what serve's
pages=on-vs-off bit-identity guarantee rests on.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import _common

__all__ = ["paged_attention", "paged_attention_reference"]

_NEG = -1e30


def paged_attention_reference(q, k_pages, v_pages, tables, t):
    """Pure-XLA paged decode attention (the pre-kernel lowering).

    q (B,H,1,D); k_pages/v_pages (P,H,ps,D); tables (B,n_pg) int32 page
    ids; t (B,) traced int positions. Returns (B,H,1,D) in q.dtype.

    Gathers the pages into the dense (B,H,L,D) layout (L = n_pg*ps) and
    then runs VERBATIM the masked f32 score/softmax/PV expression of the
    dense slot-cache step — identical operand shapes, identical
    reductions, so a paged cache whose tables enumerate a sequence's
    pages in order produces bit-identical logits to the dense cache."""
    ti = t.astype(jnp.int32)
    kc = k_pages[tables]                         # (B, n_pg, H, ps, D)
    B, n_pg, H, ps, D = kc.shape
    kc = kc.transpose(0, 2, 1, 3, 4).reshape(B, H, n_pg * ps, D)
    vc = v_pages[tables].transpose(0, 2, 1, 3, 4) \
        .reshape(B, H, n_pg * ps, D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / (D ** 0.5)
    valid = jnp.arange(kc.shape[2])[None, None, None, :] \
        <= ti[:, None, None, None]
    s = jnp.where(valid, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p,
                   vc.astype(jnp.float32)).astype(q.dtype)
    return o


# --------------------------------------------------------------------------
# pallas kernel
# --------------------------------------------------------------------------

def _kernel(tb_ref, t_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *,
            page_size, sm_scale):
    """One (batch row, page) program: online-softmax accumulate this
    page's contribution to row b's single-query attention.

    The page-table gather happens OUTSIDE this body — the k/v BlockSpec
    index_map reads the scalar-prefetched table, so k_ref/v_ref already
    hold page tables[b, j] in VMEM. Scratch (m, l, acc) carries the
    running max / denominator / value-sum across the page ('arbitrary')
    grid dimension; lanes-broadcast (H, 128) carriers keep the row
    vectors in Mosaic-friendly tiles."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pg = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full(m_s.shape, _NEG, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    q = q_ref[0].astype(jnp.float32)                     # (H, D)
    k = k_ref[0].astype(jnp.float32)                     # (H, ps, D)
    v = v_ref[0].astype(jnp.float32)
    H, ps, _ = k.shape
    # per-head single-query scores over this page's positions
    s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale                                     # (H, ps)
    pos = j * page_size + \
        jax.lax.broadcasted_iota(jnp.int32, (H, ps), 1)
    s = jnp.where(pos <= t_ref[b], s, _NEG)

    m_prev = m_s[:, 0:1]                                 # (H, 1)
    l_prev = l_s[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                               # (H, ps)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc_s[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)              # (H, D)
    m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
    l_s[...] = jnp.broadcast_to(l_new, l_s.shape)
    acc_s[...] = acc

    @pl.when(j == n_pg - 1)
    def _write():
        o_ref[0] = (acc_s[...] / l_s[:, 0:1]).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, tables, t):
    B, H, _, D = q.shape
    ps = k_pages.shape[2]
    n_pg = tables.shape[1]
    q2 = q.reshape(B, H, D)
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=ps,
                          sm_scale=1.0 / (D ** 0.5)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, n_pg),
            in_specs=[
                pl.BlockSpec((1, H, D),
                             lambda b, j, tb, tt: (b, 0, 0)),
                pl.BlockSpec((1, H, ps, D),
                             lambda b, j, tb, tt: (tb[b, j], 0, 0, 0)),
                pl.BlockSpec((1, H, ps, D),
                             lambda b, j, tb, tt: (tb[b, j], 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, H, D),
                                   lambda b, j, tb, tt: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((H, 128), jnp.float32),       # running max
                pltpu.VMEM((H, 128), jnp.float32),       # denominator
                pltpu.VMEM((H, D), jnp.float32),         # value acc
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=_common.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_common.interpret(),
    )(tables.astype(jnp.int32), t.astype(jnp.int32), q2, k_pages, v_pages)
    return out.reshape(B, H, 1, D)


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def paged_attention(q, k_pages, v_pages, tables, t):
    """Single-query decode attention through a page table.

    Args:
      q: (B, H, 1, D) queries (model dtype).
      k_pages, v_pages: (P, H, page_size, D) pooled KV pages (cache
        dtype) — page id p is physical row p.
      tables: (B, n_pg) int32 page ids; row b's logical position range
        [0, n_pg*page_size) maps page-major onto its table entries.
      t: (B,) traced int — row b attends positions <= t[b].

    Returns (B, H, 1, D) in q.dtype. `kernels=off` (or no
    TPU/interpreter) runs `paged_attention_reference` — bit-identical to
    the dense slot-cache attention at the same gathered shapes. Like the
    fused-update kernels, the Pallas path is a global-view
    `pallas_call` with no GSPMD rule, so it engages only when the step
    sees a single device (serve's decode regime)."""
    if _common.use_pallas() and not _common.multi_device():
        _load_pallas()
        return _paged_attention_pallas(q, k_pages, v_pages, tables, t)
    return paged_attention_reference(q, k_pages, v_pages, tables, t)


# pallas binds lazily at first kernel engagement (shared logic in
# _common): this module sits on the serve decode hot path, and with
# kernels=off it must not drag jax.experimental.pallas into the process
# (ci sanity asserts it)
pl = None
pltpu = None


def _load_pallas():
    global pl, pltpu
    pl = _common.load_pallas()
    if pltpu is None:
        from jax.experimental.pallas import tpu as _pltpu
        pltpu = _pltpu
