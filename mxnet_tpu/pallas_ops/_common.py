"""Shared plumbing for the mx.kernels Pallas library.

Every kernel in this package sits behind the `kernels` knob with a
bit-exact XLA-native fallback:

  * `off`  — the fallback runs unconditionally; nothing in this module
    touches `jax.experimental.pallas` (the trainer hot loop stays free
    of the pallas import, asserted by ci/run.sh sanity).
  * `auto` (default) — the Pallas kernel engages when it can win: a TPU
    backend (or the Pallas interpreter under
    MXNET_TPU_PALLAS_INTERPRET=1, which is how tier-1 exercises the
    kernel CODE on CPU) and, for the elementwise fused-update kernels,
    at least `kernels_min_elements` elements.
  * `on`   — insist: `require()` raises when Pallas is unavailable
    instead of silently falling back (shape-eligibility still applies —
    `on` cannot make a non-divisible layout divisible).

The eligibility decision is made at TRACE time (plain Python, outside
the compiled computation), so `off` runs are byte-identical to a build
without this package: the fallback expression IS the pre-kernel code.

SPMD caveat, shared by every kernel here: `pl.pallas_call` has no GSPMD
partitioning rule, so inside an SPMD-jitted step on a multi-device mesh
the partitioner would resolve it by gather-to-replicated — worse than
the XLA lowering it replaces. Kernels that run inside `shard_map`
(`parallel/moe.py` — per-device manual code) engage on any mesh; the
global-view fused-update kernels engage only when one process sees one
device (`multi_device()` is False). The per-shard MATH composes with
mx.zero regardless — `tests/unittest/test_kernels.py` pins that a
sharded application (kernel per flat shard) is bit-exact against the
whole-vector kernel.
"""
from __future__ import annotations

import os

from .. import config as _config

__all__ = ["interpret", "pallas_available", "use_pallas", "require",
           "multi_device", "min_elements", "load_pallas",
           "compiler_params", "round_up", "row8"]

# the pallas module, bound by load_pallas() at first kernel engagement —
# ONE copy of the lazy-import logic for the whole library (kernels=off /
# CPU processes never call it, so pallas stays out of sys.modules)
pl = None


def load_pallas():
    global pl
    if pl is None:
        from jax.experimental import pallas as pl_mod
        pl = pl_mod
    return pl


def compiler_params(**kw):
    """TPU compiler params under the post-rename spelling: jax 0.4.x
    calls it TPUCompilerParams, newer jax CompilerParams — resolved here
    ONCE for every kernel module (a jax rename is a one-line fix)."""
    from jax.experimental.pallas import tpu as pltpu
    cp = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cp(**kw)


def smem():
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.SMEM


def round_up(x, m):
    return (x + m - 1) // m * m


def row8(x):
    """(N,) -> (8, N): the 8-sublane carrier layout for row vectors
    (the flash_attention LSE/bias convention — Mosaic wants the last two
    block dims (8k, 128k) or spanning the array)."""
    import jax.numpy as jnp
    return jnp.broadcast_to(x[None, :], (8, x.shape[0]))


def interpret():
    """MXNET_TPU_PALLAS_INTERPRET=1 routes every kernel through the
    Pallas interpreter on any backend — the only way the kernel CODE
    (not the jnp fallback) is exercised off-TPU (tier-1 + ci sanity)."""
    return os.environ.get("MXNET_TPU_PALLAS_INTERPRET", "0") == "1"


def pallas_available():
    """True when a TPU backend (or the interpreter) can run a kernel
    AND the pallas import succeeds. The backend test comes FIRST: on a
    CPU backend without the interpreter this returns False without ever
    importing `jax.experimental.pallas`, so a kernels=auto process on
    CPU — and any kernels=off process — keeps pallas out of sys.modules
    entirely (ci/run.sh sanity asserts it after a trainer step +
    QuantizedDense forward)."""
    if not interpret():
        import jax
        if jax.default_backend() != "tpu":
            return False
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except Exception:        # pragma: no cover - pallas ships with jax
        return False
    return True


def use_pallas():
    """The per-call-site gate: False under kernels=off (no pallas
    import, no backend probe), else whether a kernel can actually run
    here. `on` behaves like `auto` for the decision itself — it differs
    only in that `require()` raises instead of falling back."""
    knob = _config.get("kernels")
    if knob == "off":
        return False
    ok = pallas_available()
    if not ok and knob == "on":
        require()
    return ok


def require():
    """kernels='on' insists: raise naming the reason Pallas cannot run
    instead of a silent fallback (auto's behavior)."""
    if not pallas_available():
        import jax
        raise RuntimeError(
            "kernels='on' but the Pallas path cannot run here: backend "
            f"is {jax.default_backend()!r} (need TPU, or "
            "MXNET_TPU_PALLAS_INTERPRET=1 for the interpreter). Use "
            "kernels='auto' to fall back to the XLA lowering silently.")


def multi_device():
    """True when the step being traced spans more than one device — the
    SPMD regime where a pallas_call inside a global-view jit would be
    resolved by gather-to-replicated (see module docstring). The
    installed parallel mesh is the authority when one exists (a 1-device
    mesh on an 8-device host is still a single-device step); otherwise
    the local device count decides. Checked at trace time; never
    cold-inits a backend beyond what jit already did."""
    try:
        from ..parallel import mesh as _mesh
        m = _mesh._current.get("mesh")
        if m is not None:
            return int(m.size) > 1
    except Exception:        # pragma: no cover
        pass
    import jax
    try:
        return jax.local_device_count() > 1
    except Exception:        # pragma: no cover
        return True


def min_elements():
    return int(_config.get("kernels_min_elements"))
