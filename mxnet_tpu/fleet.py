"""mx.fleet — the replicated serving gang: N `mx.serve.Server` worker
processes behind one health-routed, stdlib-only front door.

Every serve-side capability below this layer (continuous batching,
paged KV, SLOs, goodput) lives in a single process; mx.fleet is the
layer that survives a process. It extends the memory-safe-by-prediction
discipline (arxiv 2206.14148 — never dispatch a predicted overrun) up
one level: never ROUTE to a replica whose published admission headroom
predicts a 429.

Two halves, one file:

* **Replica side** (`ReplicaEndpoint`, `run_replica`) — runs inside a
  worker process next to a `serve.Server`. One ndjson-streaming HTTP
  surface: `POST /submit` (tokens as they decode, `skip` high-water for
  replay), `GET /healthz` / `GET /statusz` (liveness + the placement
  payload: queue depth, slot occupancy, p99 queue wait, memsafe
  admission hints), `POST /drain`. SIGTERM is flag-only: stop new
  admits, finish in-flight work inside `fleet_drain_grace_s`, requeue
  the rest with a retriable verdict, exit through the resilience
  preemption path (exit code 83) so the supervisor records a graceful
  drain, not a crash.

* **Router side** (`Router`, `RouterServer`) — stdlib-only (importable
  by path from `tools/launch.py`, no jax, no package). Health-polls
  every replica on a fixed cadence, places each request on the
  least-loaded eligible replica (skipping draining, unhealthy and
  predicted-429 replicas), and fails over mid-stream: a replica that
  dies (or wedges past `fleet_stall_timeout_ms`) has its in-flight
  requests re-submitted to survivors with `skip` set to the high-water
  mark of tokens already delivered — generation is deterministic per
  request, so the client's concatenated stream is bit-identical to an
  unloaded solo run and no token is ever re-sent (the serve
  evict-requeue replay contract, one level up). Rolling updates drain
  one replica at a time; queue-wait autoscale asks the supervisor for
  more (or fewer) replicas on sustained p99 queue-wait pressure.

fleet=off is the zero-overhead fast path: nothing here is constructed,
and every hook site elsewhere (the mx.scope statusz section) reduces to
one module-bool check — asserted by ci/run.sh fleet.
"""
from __future__ import annotations

import argparse
import collections
import http.client
import json
import os
import signal as _signal
import socket
import sys
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = [
    "ReplicaEndpoint", "Router", "RouterServer", "FleetRequest",
    "enable", "disable", "enabled", "snapshot", "run_replica",
    "EXIT_PREEMPTED",
]

#: mirror of mxnet_tpu.resilience.EXIT_PREEMPTED — the router half of
#: this module must stay importable by path with no package around it
EXIT_PREEMPTED = 83

_enabled = False
_endpoints = weakref.WeakSet()


def enabled():
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def snapshot():
    """Replica-side fleet state for the mx.scope statusz section (one
    dict per live endpoint). Callers gate on `_enabled` — this is never
    reached on the fleet=off fast path."""
    return {"endpoints": [ep.describe() for ep in list(_endpoints)]}


def _percentile(values, pct):
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round((pct / 100.0) * (len(vs) - 1)))))
    return vs[idx]


# ---------------------------------------------------------------------------
# replica side
# ---------------------------------------------------------------------------

class _StreamAborted(Exception):
    """Raised inside a /submit handler when the endpoint is simulating
    replica death (`kill()`): the connection closes mid-stream with no
    terminal line — exactly what a SIGKILLed process looks like to the
    router."""


class ReplicaEndpoint:
    """The in-process serving endpoint one fleet replica exports.

    Wraps a live `serve.Server`; `port=0` binds an ephemeral port
    (tests, benchmarks). The launcher layout is `fleet_port + 1 + R`
    for replica R — same base+1+rank convention as mx.scope."""

    def __init__(self, server, replica=None, port=0, host="127.0.0.1",
                 version=None):
        enable()
        self.server = server
        self.replica = int(replica if replica is not None
                           else os.environ.get("MXNET_TPU_FLEET_REPLICA", 0))
        self.version = version if version is not None \
            else os.environ.get("MXNET_TPU_FLEET_VERSION", "v0")
        self.host = host
        self.draining = False
        self._dead = False                  # test-only simulated SIGKILL
        self._slow_ms = None                # slow_replica fault, once armed
        self._slow_checked = False
        self._qwaits = collections.deque(maxlen=256)
        self._served = 0
        self._requeued_out = 0
        self._lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, int(port)),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name=f"mx-fleet-replica-{self.replica}", daemon=True)
        self._thread.start()
        _endpoints.add(self)

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def describe(self):
        return {"replica": self.replica, "version": self.version,
                "port": self.port, "draining": self.draining,
                "served": self._served, "requeued_out": self._requeued_out,
                "pid": os.getpid()}

    # -- drain / death ---------------------------------------------------
    def begin_drain(self):
        """Stop admitting new fleet requests (router submits answer
        `503 draining`, retriable). In-flight requests keep decoding."""
        self.draining = True

    def drain_and_requeue(self, grace_s=None):
        """Finish in-flight requests for up to `grace_s`, then cancel
        the stragglers with a retriable verdict so the router requeues
        them on a survivor (their streams carry the replay high-water).
        Returns (finished, requeued)."""
        if grace_s is None:
            grace_s = float(os.environ.get("MXNET_TPU_FLEET_DRAIN_GRACE_S",
                                           30.0))
        self.begin_drain()
        deadline = time.monotonic() + float(grace_s)
        finished = 0
        while self.server.busy() and time.monotonic() < deadline:
            time.sleep(0.01)
        from mxnet_tpu import serve as _serve
        with self.server._lock:
            live = [r for r in self.server._by_id.values()
                    if r.state not in _serve.TERMINAL]
        for r in live:
            self.server.cancel(r)
            self._requeued_out += 1
        # let the scheduler apply the cancels so every stream terminates
        t0 = time.monotonic()
        while self.server.busy() and time.monotonic() - t0 < 5.0:
            time.sleep(0.01)
        finished = self._served - self._requeued_out
        return finished, len(live)

    def kill(self):
        """Simulate abrupt replica death in-process (tests): in-flight
        /submit streams break mid-token with no terminal line, and
        health checks start failing. The real drill is a SIGKILLed
        worker process; this is its single-process stand-in."""
        self._dead = True

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- payloads --------------------------------------------------------
    def statusz(self):
        st = self.server.stats()
        with self._lock:
            qw = list(self._qwaits)
        p99 = _percentile(qw, 99)
        out = {"replica": self.replica, "version": self.version,
               "pid": os.getpid(), "draining": self.draining,
               "stats": st,
               "queue_wait_p99_ms": round(p99 * 1e3, 3)
               if p99 is not None else None,
               "admission": self.server.admission_hints(),
               "served": self._served,
               "requeued_out": self._requeued_out}
        try:
            from mxnet_tpu import telemetry as _telemetry
            if _telemetry._enabled:
                h = _telemetry.get("serve_ttft_seconds")
                if h.count:
                    out["ttft_p99_ms"] = round(
                        (h.percentile(99) or 0) * 1e3, 3)
        except Exception:
            pass
        return out

    def _maybe_slow_ms(self):
        """slow_replica:ms fault — the SERVER side of slow_client: every
        streamed token leaves this replica `ms` late, so the router's
        placement (TTFT percentiles) must learn to route around it."""
        if self._slow_checked:
            return self._slow_ms
        self._slow_checked = True
        try:
            from mxnet_tpu import resilience as _resilience
        except Exception:
            return None
        inj = _resilience._injector if _resilience._enabled else None
        if inj is not None:
            arg = inj.consume("slow_replica")
            if arg:
                self._slow_ms = float(arg)
                print(f"mx.fleet: fault injection: slow replica "
                      f"{self.replica} — {arg} ms per streamed token",
                      file=sys.stderr)
        return self._slow_ms

    # -- http ------------------------------------------------------------
    def _make_handler(self):
        ep = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"   # Connection: close == stream EOF

            def log_message(self, *args):
                pass

            def _send_json(self, code, payload):
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if ep._dead:
                    # dead-host simulation: no status line, connection
                    # closes — the fetcher sees exactly a SIGKILLed peer
                    self.close_connection = True
                    return
                if self.path == "/healthz":
                    self._send_json(200, {
                        "ok": True, "replica": ep.replica,
                        "version": ep.version, "draining": ep.draining,
                        "pid": os.getpid()})
                elif self.path == "/statusz":
                    self._send_json(200, ep.statusz())
                else:
                    self._send_json(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                if ep._dead:
                    self.close_connection = True
                    return
                if self.path == "/drain":
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        body = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        body = {}
                    if body.get("off"):
                        ep.draining = False
                    else:
                        ep.begin_drain()
                    self._send_json(200, {"draining": ep.draining,
                                          "replica": ep.replica})
                    return
                if self.path != "/submit":
                    self._send_json(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send_json(400, {"error": "bad json"})
                    return
                ep._handle_submit(self, body)

        return Handler

    def _handle_submit(self, handler, body):
        from mxnet_tpu import serve as _serve
        if self.draining:
            handler._send_json(200, {
                "done": True, "state": _serve.SHED,
                "verdict": f"503 draining: replica {self.replica}",
                "retriable": True, "n": 0, "replica": self.replica,
                "version": self.version})
            return
        skip = int(body.get("skip", 0))
        try:
            req = self.server.submit(
                body["prompt"],
                max_new_tokens=int(body.get("max_new_tokens", 32)),
                eos=body.get("eos"),
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                seed=int(body.get("seed", 0)),
                deadline_ms=body.get("deadline_ms"))
        except ValueError as e:
            handler._send_json(400, {"error": str(e)})
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.end_headers()
        slow_ms = self._maybe_slow_ms()
        i = 0
        try:
            for tok in req.stream():
                if self._dead:
                    raise _StreamAborted()
                if i >= skip:
                    handler.wfile.write(
                        (json.dumps({"t": int(tok)}) + "\n").encode())
                    handler.wfile.flush()
                i += 1
                if slow_ms:
                    time.sleep(slow_ms / 1000.0)
            if self._dead:
                raise _StreamAborted()
            final = {"done": True, "state": req.state,
                     "verdict": req.verdict, "n": len(req.tokens),
                     "requeues": req.requeues, "replica": self.replica,
                     "version": self.version}
            # a drain-expiry cancellation is the router's cue to replay
            # this request on a survivor (skip = what we already sent)
            if self.draining and req.state == _serve.CANCELLED:
                final["retriable"] = True
            handler.wfile.write((json.dumps(final) + "\n").encode())
            handler.wfile.flush()
            with self._lock:
                self._served += 1
                if req.queue_wait_s is not None:
                    self._qwaits.append(req.queue_wait_s)
        except (_StreamAborted, BrokenPipeError, ConnectionResetError):
            # dead-replica simulation or a vanished client: free the
            # slot and close without a terminal line; the router
            # replays on a survivor from its high-water mark
            self.server.cancel(req)
            handler.close_connection = True


# ---------------------------------------------------------------------------
# router side (stdlib-only: loadable by path from tools/launch.py)
# ---------------------------------------------------------------------------

class _Replica:
    __slots__ = ("rid", "url", "healthy", "draining", "hold", "stats",
                 "last_ok", "fails")

    def __init__(self, rid, url):
        self.rid = rid
        self.url = url
        self.healthy = False
        self.draining = False
        self.hold = False        # router-local traffic hold (rolling update)
        self.stats = {}
        self.last_ok = 0.0
        self.fails = 0

    def view(self):
        st = self.stats.get("stats", {})
        return {"url": self.url, "healthy": self.healthy,
                "draining": self.draining or self.hold,
                "version": self.stats.get("version"),
                "queued": st.get("queued"), "running": st.get("running"),
                "queue_wait_p99_ms": self.stats.get("queue_wait_p99_ms"),
                "fails": self.fails}


class FleetRequest:
    """The router-side request handle; mirrors the `serve.Request`
    consumer surface (`stream()` / `result(timeout)` / `state` /
    `verdict` / `tokens`) plus the fleet trail: `replicas_tried`,
    `failovers`. Tokens arriving after a failover continue the same
    stream — the replay `skip` guarantees no token repeats."""

    _EOS = object()

    def __init__(self, rid, payload):
        self.id = rid
        self.payload = payload
        self.tokens = []
        self.state = "queued"
        self.verdict = None
        self.replicas_tried = []
        self.failovers = 0
        self._q = collections.deque()
        self._cv = threading.Condition()
        self._done = threading.Event()

    @property
    def done(self):
        return self._done.is_set()

    def _push(self, tok):
        self.tokens.append(tok)
        with self._cv:
            self._q.append(tok)
            self._cv.notify_all()

    def _finish(self, state, verdict):
        self.state = state
        self.verdict = verdict
        self._done.set()
        with self._cv:
            self._q.append(self._EOS)
            self._cv.notify_all()

    def stream(self):
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait()
                item = self._q.popleft()
            if item is self._EOS:
                return
            yield item

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"fleet request {self.id} still {self.state} after "
                f"{timeout}s")
        return list(self.tokens)


class Router:
    """Health-routed load balancer over a set of replica endpoints.

    stdlib-only by design: `tools/launch.py` loads this module by path
    (no package import, no jax) and runs the router inside the launcher
    process, exactly like its `_ScopeAggregator`.

    `replicas` maps replica-id -> base URL. `on_scale(n)` — when set —
    receives the autoscaler's requested replica count; the launcher
    clamps it through `_plan_world` (the elastic world-size plumbing)
    and spawns/drains workers to match."""

    #: verdict prefixes worth one more try on a DIFFERENT replica —
    #: per-replica overload is exactly what a second replica is for
    RETRIABLE = ("503", "429")

    def __init__(self, replicas, retry_max=None, health_interval_s=None,
                 stall_timeout_s=None, connect_timeout_s=2.0,
                 autoscale=None, autoscale_p99_ms=None,
                 autoscale_window_s=None, on_scale=None,
                 clock=time.monotonic):
        env = os.environ.get
        self.retry_max = int(retry_max if retry_max is not None
                             else env("MXNET_TPU_FLEET_RETRY_MAX", 3))
        self.health_interval_s = float(
            health_interval_s if health_interval_s is not None
            else float(env("MXNET_TPU_FLEET_HEALTH_INTERVAL_MS", 250.0))
            / 1000.0)
        self.stall_timeout_s = float(
            stall_timeout_s if stall_timeout_s is not None
            else float(env("MXNET_TPU_FLEET_STALL_TIMEOUT_MS", 10000.0))
            / 1000.0)
        self.connect_timeout_s = float(connect_timeout_s)
        self.autoscale = (autoscale if autoscale is not None
                          else env("MXNET_TPU_FLEET_AUTOSCALE", "off")
                          == "on")
        self.autoscale_p99_ms = float(
            autoscale_p99_ms if autoscale_p99_ms is not None
            else env("MXNET_TPU_FLEET_AUTOSCALE_P99_MS", 500.0))
        self.autoscale_window_s = float(
            autoscale_window_s if autoscale_window_s is not None
            else env("MXNET_TPU_FLEET_AUTOSCALE_WINDOW_S", 5.0))
        self.on_scale = on_scale
        self._clock = clock
        self._lock = threading.RLock()
        self._replicas = {rid: _Replica(rid, url)
                          for rid, url in dict(replicas).items()}
        self._seq = 0
        self._rr = 0
        self.counters = collections.Counter()
        self.scale_events = []
        self._over_since = None
        self._under_since = None
        self._poll_thread = None
        self._stop = threading.Event()

    # -- membership ------------------------------------------------------
    def add_replica(self, rid, url):
        with self._lock:
            self._replicas[rid] = _Replica(rid, url)

    def remove_replica(self, rid):
        with self._lock:
            self._replicas.pop(rid, None)

    def replica_ids(self):
        with self._lock:
            return sorted(self._replicas)

    def set_url(self, rid, url):
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None:
                r.url = url

    # -- health ----------------------------------------------------------
    def start(self):
        if self._poll_thread is not None and self._poll_thread.is_alive():
            return self
        self._stop.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="mx-fleet-router", daemon=True)
        self._poll_thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)

    def _poll_loop(self):
        while not self._stop.wait(self.health_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — poll must survive
                print(f"mx.fleet: health poll error: {e}", file=sys.stderr)

    def _get_json(self, url, timeout):
        import urllib.request
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())

    def poll_once(self):
        """One synchronous health pass over every replica: /healthz for
        liveness, /statusz for the placement payload. A replica that
        fails the fetch is unhealthy until a later pass succeeds."""
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            try:
                hz = self._get_json(r.url + "/healthz",
                                    self.connect_timeout_s)
                st = self._get_json(r.url + "/statusz",
                                    self.connect_timeout_s)
            except Exception:
                r.healthy = False
                r.fails += 1
                continue
            r.healthy = bool(hz.get("ok"))
            r.draining = bool(hz.get("draining"))
            r.stats = st
            r.last_ok = self._clock()
            r.fails = 0
        if self.autoscale:
            self.maybe_autoscale()

    # -- admission prediction -------------------------------------------
    @staticmethod
    def predict_429(statusz, need):
        """True when the replica's PUBLISHED admission hints predict a
        429 for a request of `need` total tokens (prompt + max_new):
        the dense bucket it would newly allocate costs more than the
        published memsafe headroom, or — paged — the pool lacks the
        pages. Unknown headroom (memsafe off) predicts nothing."""
        hints = (statusz or {}).get("admission") or {}
        max_len = hints.get("max_len")
        if max_len and need > int(max_len):
            return True                      # 413, but equally unroutable
        headroom = hints.get("headroom_bytes")
        if headroom is None:
            return False
        if hints.get("pages") == "on":
            ps = int(hints.get("page_size") or 0)
            free = hints.get("pool_pages_free")
            if ps and free is not None:
                return (need + ps - 1) // ps > int(free)
            return False
        buckets = hints.get("buckets")
        if buckets:
            cands = [int(b) for b in buckets if int(b) >= need]
            if not cands:
                return True
            bucket = min(cands)
        else:
            bucket = 1
            while bucket < need:
                bucket *= 2
            if max_len:
                bucket = min(bucket, int(max_len))
        allocated = set(int(b) for b in
                        (statusz.get("stats", {})
                         .get("buckets_allocated") or []))
        if bucket in allocated:
            return False                     # cache exists; no new cost
        cost = (hints.get("bucket_cost") or {}).get(str(bucket))
        if cost is None:
            return False
        return int(cost) > int(headroom)

    # -- placement -------------------------------------------------------
    def _place(self, need, exclude=()):
        """Least-loaded eligible replica for a `need`-token request, or
        None. Eligible = healthy, not draining/held, not excluded, not
        predicted to 429."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.healthy and not r.draining and not r.hold
                    and r.rid not in exclude]
            cands = []
            for r in reps:
                if need and self.predict_429(r.stats, need):
                    self.counters["skipped_admission"] += 1
                    continue
                st = r.stats.get("stats", {})
                slots = (r.stats.get("admission") or {}).get("slots") or 1
                load = (st.get("queued", 0)
                        + st.get("running", 0) / max(1, slots))
                cands.append((load, r.stats.get("ttft_p99_ms") or 0.0, r))
            if not cands:
                return None
            cands.sort(key=lambda c: (c[0], c[1], c[2].rid))
            best = cands[0][0]
            ties = [c[2] for c in cands if c[0] == best]
            self._rr += 1
            return ties[self._rr % len(ties)]

    def _mark_dead(self, rid):
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None:
                r.healthy = False
                r.fails += 1

    # -- submit / failover ----------------------------------------------
    def submit(self, prompt, max_new_tokens=32, eos=None, temperature=0.0,
               top_k=0, seed=0, deadline_ms=None):
        """Route one generation request; returns a FleetRequest
        immediately. Never raises for overload — exhausting every
        replica (or the failover budget) lands a 503 verdict on the
        request, mirroring `serve.Server.submit`."""
        with self._lock:
            rid = self._seq
            self._seq += 1
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new_tokens": int(max_new_tokens), "eos": eos,
                   "temperature": float(temperature), "top_k": int(top_k),
                   "seed": int(seed), "deadline_ms": deadline_ms}
        freq = FleetRequest(rid, payload)
        self.counters["submitted"] += 1
        t = threading.Thread(target=self._drive, args=(freq,),
                             name=f"mx-fleet-req-{rid}", daemon=True)
        t.start()
        return freq

    def _drive(self, freq):
        need = len(freq.payload["prompt"]) + freq.payload["max_new_tokens"]
        overloaded = set()     # replicas that answered a retriable verdict
        last_verdict = None
        attempts = 0
        backoff = 0.05
        while True:
            rep = self._place(need, exclude=overloaded)
            if rep is None and overloaded:
                # every healthy replica answered overload: accept the
                # freshest overload verdict rather than spinning
                freq._finish("shed" if (last_verdict or "").startswith(
                    "503") else "rejected",
                    last_verdict or "503 fleet: all replicas overloaded")
                return
            if rep is None:
                attempts += 1
                if attempts > self.retry_max:
                    freq._finish(
                        "failed",
                        "503 fleet: no healthy replica "
                        f"(tried {freq.replicas_tried})")
                    return
                time.sleep(backoff)
                backoff = min(1.0, backoff * 2)
                self.poll_once()
                continue
            freq.replicas_tried.append(rep.rid)
            kind, info = self._attempt(rep, freq)
            if kind == "final":
                self.counters["completed"] += 1
                freq._finish(info["state"], info["verdict"])
                return
            if kind == "overloaded":
                overloaded.add(rep.rid)
                last_verdict = info
                self.counters["retries"] += 1
                continue
            # transport death / stall / drain-requeue: failover
            self.counters["failovers"] += 1
            freq.failovers += 1
            if info == "dead":
                self._mark_dead(rep.rid)
            attempts += 1
            if attempts > self.retry_max:
                freq._finish(
                    "failed",
                    f"503 fleet: failover budget exhausted after "
                    f"{freq.failovers} failover(s) "
                    f"(tried {freq.replicas_tried})")
                return
            time.sleep(backoff)
            backoff = min(1.0, backoff * 2)

    def _attempt(self, rep, freq):
        """One streaming /submit attempt against `rep`, resuming past
        the tokens already delivered. Returns ("final", {...}),
        ("overloaded", verdict) or ("failover", "dead"|"requeue")."""
        body = dict(freq.payload)
        body["skip"] = len(freq.tokens)       # the replay high-water mark
        host, _, port = rep.url.rpartition("//")[2].partition(":")
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=self.connect_timeout_s)
        try:
            conn.request("POST", "/submit", json.dumps(body).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            if self.stall_timeout_s and conn.sock is not None:
                # per-read stall bound: a wedged-but-alive replica stops
                # producing tokens without closing the socket
                conn.sock.settimeout(self.stall_timeout_s)
            if resp.status != 200:
                return "failover", "dead"
            while True:
                line = resp.readline()
                if not line:
                    # EOF with no terminal line: the replica died
                    # mid-stream (SIGKILL / kill())
                    return "failover", "dead"
                try:
                    msg = json.loads(line)
                except ValueError:
                    return "failover", "dead"
                if "t" in msg:
                    freq._push(int(msg["t"]))
                    continue
                if msg.get("done"):
                    verdict = msg.get("verdict") or ""
                    if msg.get("retriable"):
                        return "failover", "requeue"
                    if verdict[:3] in ("503", "429") \
                            and msg.get("n", 0) == 0 \
                            and not freq.tokens:
                        return "overloaded", verdict
                    return "final", {"state": msg.get("state", "done"),
                                     "verdict": verdict}
        except (OSError, http.client.HTTPException, socket.timeout):
            return "failover", "dead"
        finally:
            try:
                conn.close()
            except Exception:
                pass

    # -- drain / rolling update -----------------------------------------
    def drain(self, rid, remote=True):
        """Hold traffic off replica `rid` (and, `remote=True`, tell the
        replica itself to refuse new admits)."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return False
            r.hold = True
            url = r.url
        if remote:
            try:
                import urllib.request
                req = urllib.request.Request(url + "/drain", data=b"{}",
                                             method="POST")
                urllib.request.urlopen(req, timeout=self.connect_timeout_s)
            except Exception:
                pass
        return True

    def undrain(self, rid, remote=True):
        """Release a router-local hold; `remote=True` also clears the
        replica's own draining refusal (a rolled replica comes back
        fresh, but an ABORTED drain must re-open the old process)."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.hold = False
            r.draining = False
            url = r.url
        if remote:
            try:
                import urllib.request
                req = urllib.request.Request(
                    url + "/drain", data=b'{"off": true}', method="POST")
                urllib.request.urlopen(req, timeout=self.connect_timeout_s)
            except Exception:
                pass

    def replica_idle(self, rid):
        with self._lock:
            r = self._replicas.get(rid)
        if r is None:
            return True
        st = r.stats.get("stats", {})
        return r.healthy and st.get("queued", 1) == 0 \
            and st.get("running", 1) == 0

    def wait_idle(self, rid, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.poll_once()
            if self.replica_idle(rid):
                return True
            time.sleep(0.05)
        return False

    def wait_healthy(self, rid, timeout_s=30.0, version=None):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.poll_once()
            with self._lock:
                r = self._replicas.get(rid)
                if r is not None and r.healthy and not r.draining and (
                        version is None
                        or r.stats.get("version") == version):
                    return True
            time.sleep(0.05)
        return False

    def rolling_update(self, update_replica, version=None,
                       wait_timeout_s=30.0):
        """Replica-by-replica restart onto new weights, serving
        continuously: drain -> wait idle -> `update_replica(rid)` (may
        return a new URL) -> wait healthy (at `version`, if given) ->
        release traffic. Returns the list of updated replica ids."""
        updated = []
        for rid in self.replica_ids():
            self.drain(rid)
            self.wait_idle(rid, wait_timeout_s)
            new_url = update_replica(rid)
            if new_url:
                self.set_url(rid, new_url)
            self.wait_healthy(rid, wait_timeout_s, version=version)
            self.undrain(rid)
            updated.append(rid)
        return updated

    # -- autoscale -------------------------------------------------------
    def maybe_autoscale(self, now=None):
        """Queue-wait autoscaling with hysteresis: every healthy
        replica over the p99 threshold for a full window asks for one
        more replica; a fleet with empty queues and negligible queue
        wait for a full window gives one back. The supervisor clamps
        the request through the elastic world-size plumbing."""
        if self.on_scale is None:
            return
        now = self._clock() if now is None else now
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.healthy and not r.draining and not r.hold]
            n = len(self._replicas)
        if not reps:
            self._over_since = self._under_since = None
            return
        p99s = [r.stats.get("queue_wait_p99_ms") or 0.0 for r in reps]
        queued = sum(r.stats.get("stats", {}).get("queued", 0)
                     for r in reps)
        pressure = min(p99s)      # EVERY replica hot, not just one
        if pressure > self.autoscale_p99_ms:
            self._under_since = None
            if self._over_since is None:
                self._over_since = now
            elif now - self._over_since >= self.autoscale_window_s:
                self._over_since = None
                self.scale_events.append(
                    {"t": now, "dir": "up", "from": n, "to": n + 1,
                     "p99_ms": pressure})
                self.on_scale(n + 1)
        elif pressure < self.autoscale_p99_ms / 4.0 and queued == 0:
            self._over_since = None
            if self._under_since is None:
                self._under_since = now
            elif now - self._under_since >= self.autoscale_window_s:
                self._under_since = None
                self.scale_events.append(
                    {"t": now, "dir": "down", "from": n, "to": n - 1,
                     "p99_ms": pressure})
                self.on_scale(n - 1)
        else:
            self._over_since = self._under_since = None

    # -- views -----------------------------------------------------------
    def healthz(self):
        with self._lock:
            reps = {r.rid: {"ok": r.healthy, "draining":
                            r.draining or r.hold}
                    for r in self._replicas.values()}
        return {"ok": any(v["ok"] for v in reps.values()),
                "replicas": reps}

    def statusz(self):
        with self._lock:
            return {"replicas": {r.rid: r.view()
                                 for r in self._replicas.values()},
                    "counters": dict(self.counters),
                    "scale_events": list(self.scale_events)}


class RouterServer:
    """The fleet's one public HTTP endpoint (the `_ScopeAggregator` of
    serving): `POST /submit` streams tokens back as ndjson riding the
    router's placement + failover; `GET /healthz` / `GET /statusz` are
    the merged fleet views; `POST /roll` and `POST /scale` hand rolling
    updates and explicit resizes to the supervisor's hooks."""

    def __init__(self, router, port, host="127.0.0.1"):
        self.router = router
        self.on_roll = None
        self.on_scale = None
        self._httpd = ThreadingHTTPServer((host, int(port)),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="mx-fleet-front", daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def _make_handler(self):
        rs = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):
                pass

            def _send_json(self, code, payload):
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    self._send_json(200, rs.router.healthz())
                elif self.path == "/statusz":
                    self._send_json(200, rs.router.statusz())
                else:
                    self._send_json(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send_json(400, {"error": "bad json"})
                    return
                if self.path == "/roll":
                    if rs.on_roll is None:
                        self._send_json(501, {"error": "no supervisor"})
                    else:
                        rs.on_roll(body.get("version"))
                        self._send_json(202, {"rolling": True})
                    return
                if self.path == "/scale":
                    if rs.on_scale is None:
                        self._send_json(501, {"error": "no supervisor"})
                    else:
                        rs.on_scale(int(body["n"]))
                        self._send_json(202, {"target": int(body["n"])})
                    return
                if self.path != "/submit":
                    self._send_json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    freq = rs.router.submit(
                        body["prompt"],
                        max_new_tokens=int(body.get("max_new_tokens", 32)),
                        eos=body.get("eos"),
                        temperature=float(body.get("temperature", 0.0)),
                        top_k=int(body.get("top_k", 0)),
                        seed=int(body.get("seed", 0)),
                        deadline_ms=body.get("deadline_ms"))
                except (KeyError, ValueError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()
                try:
                    for tok in freq.stream():
                        self.wfile.write(
                            (json.dumps({"t": int(tok)}) + "\n").encode())
                        self.wfile.flush()
                    self.wfile.write((json.dumps(
                        {"done": True, "state": freq.state,
                         "verdict": freq.verdict,
                         "n": len(freq.tokens),
                         "failovers": freq.failovers,
                         "replicas_tried": freq.replicas_tried})
                        + "\n").encode())
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass

        return Handler


# ---------------------------------------------------------------------------
# replica worker entry point: python -m mxnet_tpu.fleet
# ---------------------------------------------------------------------------

def run_replica(argv=None):
    """One fleet replica worker: tiny-zoo model -> serve.Server ->
    ReplicaEndpoint (+ mx.scope when armed), then park until SIGTERM
    flags a drain — finish/requeue in-flight work within the grace
    budget and exit through the resilience preemption path (83)."""
    p = argparse.ArgumentParser(prog="python -m mxnet_tpu.fleet")
    p.add_argument("--model", default="gpt_tiny",
                   help="models.gpt config name (gpt_tiny, gpt_small, ...)")
    p.add_argument("--port", type=int, default=None,
                   help="endpoint port (default MXNET_TPU_FLEET_PORT, "
                        "else fleet_port+1+replica)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--slots", type=int, default=None)
    p.add_argument("--seed", type=int, default=0,
                   help="weight-init seed — every replica MUST share it "
                        "or failover replay breaks bit-identity")
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import config as _config
    from mxnet_tpu import parallel as _parallel
    from mxnet_tpu import resilience as _resilience
    from mxnet_tpu import scope as _scope
    from mxnet_tpu import serve as _serve
    from mxnet_tpu.models import gpt as _gpt

    replica = int(os.environ.get("MXNET_TPU_FLEET_REPLICA", 0))
    port = args.port
    if port is None:
        port = int(os.environ.get(
            "MXNET_TPU_FLEET_PORT",
            int(_config.get("fleet_port")) + 1 + replica))
    version = os.environ.get("MXNET_TPU_FLEET_VERSION", "v0")

    _parallel.make_mesh(dp=-1)
    cfg_fn = getattr(_gpt, f"{args.model}_config")
    mx.random.seed(args.seed)
    model = _gpt.GPTForCausalLM(cfg_fn())
    model.initialize()

    # SIGINT keeps the resilience preemption handler; SIGTERM belongs
    # to the fleet drain (flag-only, async-signal-safe)
    _resilience.install(signals=(_signal.SIGINT,))
    term = threading.Event()
    _signal.signal(_signal.SIGTERM, lambda *_: term.set())

    srv = _serve.Server(model, slots=args.slots).start()
    ep = ReplicaEndpoint(srv, replica=replica, port=port, host=args.host,
                         version=version)
    _scope.maybe_enable()
    grace = float(_config.get("fleet_drain_grace_s"))
    print(f"mx.fleet: replica {replica} ({version}) serving "
          f"{args.model} on {ep.url} (pid {os.getpid()})", flush=True)
    try:
        # no heartbeat here: the serve scheduler is the beat source
        # (phase="serve", every step) — if it wedges, the beat MUST go
        # stale so the supervisor's staleness kill fires
        while not term.wait(0.2):
            srv.raise_if_failed()
    except KeyboardInterrupt:
        pass
    print(f"mx.fleet: replica {replica} draining "
          f"(grace {grace:.0f}s)", flush=True)
    finished, requeued = ep.drain_and_requeue(grace)
    srv.stop()
    ep.stop()
    print(f"mx.fleet: replica {replica} drained — {finished} finished, "
          f"{requeued} requeued elsewhere; exiting via preemption path",
          flush=True)
    raise _resilience.PreemptedExit(
        f"fleet replica {replica} drained", code=_resilience.EXIT_PREEMPTED)


if __name__ == "__main__":
    run_replica()
