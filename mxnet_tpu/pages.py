"""mx.pages — block-granular paged KV cache with prefix reuse.

mx.serve's dense scheduler (PR 12) gives every request a slot in a
(slots, H, bucket, D) cache per layer: memory is owned per-slot, whole
prompts prefill one token per step, and two requests sharing a system
prefix each recompute and store it. This module is the vLLM/
PagedAttention answer (PAPERS.md 2309.06180) adapted to this runtime:

  * **PagePool** — the KV store is one pooled (pages, H, page_size, D)
    array per layer; a request owns a LIST of fixed-size pages instead
    of a dense span. Pages are refcounted: the prefix tree and every
    request sharing a block hold one reference each, and a page returns
    to the free list when the last reference drops. The pool is sized
    once at server construction and priced through the same
    mx.memsafe admission path as the dense caches
    (`Server._admit_budget` / `aot_exec_peak`).
  * **PrefixTree** — a content-hashed radix tree over FULL prompt
    blocks (SGLang-style radix cache). A finished prefill inserts its
    full prompt pages; a later request walks its prompt block-by-block
    and starts mid-cache with the matched pages mapped read-only into
    its page table (refcount bumped — prefill work is skipped, not
    copied). Hash collisions are harmless: every node stores its block
    tokens and parent digest, and a lookup verifies both before
    trusting the digest.
  * **copy-on-write** — a request never writes a page it does not own
    exclusively. When its first write position lands INSIDE a shared
    page (a fully-matched prompt recomputes its last token to get
    logits), the page is copied into a fresh one at admission
    (`PagePool.copy_page`) and the shared reference dropped.
  * **eviction** — under page pressure the server evicts tree-held
    pages LRU-leaf-first (`PrefixTree.evict`); a page still referenced
    by a running request survives until that request drains. Freed
    pages go straight back to the pool — the "pages reclaimed" half of
    the serve degradation ladder, now at page granularity.

Layout invariant: page id `p` addresses physical row `p` in EVERY
pooled array — all layers, K and V, and (when a drafter serves
speculative decoding) the drafter's arrays too. One allocator, one
refcount, one page table per request covers the whole model stack.
Pages `0..scratch-1` are per-slot scratch: masked-out lanes of a
batched step write there so real pages are never polluted.

Cost model: DISABLED (the default) is the production fast path —
`pages=off` serving never constructs a pool and never calls into this
module (ci/run.sh pages asserts zero calls across a full dense request
lifecycle; the scheduler checks one attribute). Constructing a paged
`serve.Server` arms it.
"""
from __future__ import annotations

import collections
import hashlib
import itertools

import numpy as np

__all__ = [
    "PagePool", "PrefixTree", "PagesExhausted",
    "enable", "disable", "enabled",
]

_enabled = False


def enabled():
    """True while a paged server is armed (serve.Server(pages='on')
    constructs the pool and flips this; the off path never reaches this
    module)."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


class PagesExhausted(RuntimeError):
    """The pool cannot satisfy an allocation — admission control's
    signal to walk the degradation ladder (tree eviction, shrink,
    evict-and-requeue), never a device OOM."""

    def __init__(self, need, free):
        self.need = int(need)
        self.free = int(free)
        super().__init__(
            f"page pool exhausted: need {need} pages, {free} free")


def _block_digest(parent, block_bytes):
    """Content hash of one prompt block, chained through the parent
    digest — the radix-tree node key. Collisions are tolerated (nodes
    verify tokens + parent on lookup), so the digest only has to be
    cheap and stable."""
    return hashlib.blake2b(parent + block_bytes, digest_size=16).digest()


# ---------------------------------------------------------------------------
# PagePool
# ---------------------------------------------------------------------------

class PagePool:
    """Refcounted fixed-size KV pages over pooled per-layer arrays.

    `streams` maps a tag ('target', and 'draft' when a speculative
    drafter is attached) to a list of (heads, head_dim, dtype) specs —
    one per pooled array (2 * n_layers: K then V). Every array is
    allocated as (pages, heads, page_size, head_dim) zeros; page id p
    is physical row p in all of them.

    Page-table metadata (refcounts, free list) lives host-side and is
    guarded by the owning Server's lock; the device arrays in
    `self.state[tag]` are threaded (donated) through the paged step
    executables by the scheduler thread only."""

    def __init__(self, page_size, data_pages, scratch_pages, streams):
        if page_size < 1 or data_pages < 1:
            raise ValueError(
                f"PagePool needs page_size >= 1 and data_pages >= 1, got "
                f"{page_size}/{data_pages}")
        self.page_size = int(page_size)
        self.scratch = int(scratch_pages)
        self.num_pages = self.scratch + int(data_pages)
        self.refcount = np.zeros(self.num_pages, np.int32)
        self.free = collections.deque(range(self.scratch, self.num_pages))
        self.state = {}
        self._specs = {tag: list(specs) for tag, specs in streams.items()}
        import jax.numpy as jnp
        for tag, specs in self._specs.items():
            self.state[tag] = [
                jnp.zeros((self.num_pages, h, self.page_size, d), dt)
                for (h, d, dt) in specs]
        self.stats = {"allocs": 0, "frees": 0, "cow_copies": 0,
                      "peak_used": 0}

    # -- accounting ------------------------------------------------------
    @property
    def data_pages(self):
        return self.num_pages - self.scratch

    def free_pages(self):
        return len(self.free)

    def used_pages(self):
        return self.data_pages - len(self.free)

    def pool_bytes(self):
        return sum(int(a.nbytes) for arrs in self.state.values()
                   for a in arrs)

    # -- alloc / refcount ------------------------------------------------
    def alloc(self, n):
        """Take `n` pages off the free list (refcount 1 each). Raises
        PagesExhausted — with the accounting — when the list is short;
        nothing is allocated partially."""
        if n > len(self.free):
            raise PagesExhausted(n, len(self.free))
        pages = [self.free.popleft() for _ in range(int(n))]
        for p in pages:
            self.refcount[p] = 1
        self.stats["allocs"] += len(pages)
        self.stats["peak_used"] = max(self.stats["peak_used"],
                                      self.used_pages())
        return pages

    def incref(self, page):
        if self.refcount[page] <= 0:
            raise RuntimeError(f"incref on free page {page}")
        self.refcount[page] += 1

    def decref(self, page):
        """Drop one reference; the page returns to the free list when
        the count reaches zero (its stale contents are harmless — every
        position is rewritten before the causal mask can see it)."""
        c = int(self.refcount[page])
        if c <= 0:
            raise RuntimeError(f"decref on free page {page}")
        self.refcount[page] = c - 1
        if c == 1:
            self.free.append(int(page))
            self.stats["frees"] += 1

    def copy_page(self, src):
        """Copy-on-write: allocate a fresh page and device-copy `src`'s
        row in every pooled array (all tags — the drafter's K/V for a
        block must travel with the target's). Returns the new page id;
        the caller drops its shared reference on `src`."""
        (dst,) = self.alloc(1)
        for tag, arrs in self.state.items():
            self.state[tag] = [a.at[dst].set(a[src]) for a in arrs]
        self.stats["cow_copies"] += 1
        return dst


# ---------------------------------------------------------------------------
# PrefixTree
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("digest", "parent", "block", "page", "children",
                 "last_used")

    def __init__(self, digest, parent, block, page, stamp):
        self.digest = digest
        self.parent = parent          # parent digest (b"" at the root)
        self.block = block            # the block's token bytes
        self.page = int(page)
        self.children = set()         # child digests
        self.last_used = stamp


class PrefixTree:
    """Content-hashed radix tree over full prompt blocks: digest(node) =
    blake2b(digest(parent) + block_tokens). Only FULL pages are shared —
    a partial tail block stays exclusively owned by its request (the
    "partial-block tail" rule the tests pin).

    The tree holds ONE pool reference per node; `match` bumps the
    refcount of every returned page (the caller owns those references),
    `insert` adopts a request's page into a new node (one more ref),
    and `evict` walks leaf nodes LRU-first, dropping the tree's
    reference so idle cached pages return to the pool under pressure."""

    def __init__(self, pool):
        self.pool = pool
        self.nodes = {}                         # digest -> _Node
        self.roots = set()                      # digests with parent b""
        self._stamp = itertools.count(1)        # deterministic LRU clock
        self.stats = {"hits": 0, "misses": 0, "matched_tokens": 0,
                      "inserted_pages": 0, "evicted_pages": 0}

    def __len__(self):
        return len(self.nodes)

    def _blocks(self, prompt):
        ps = self.pool.page_size
        prompt = np.asarray(prompt, np.int32)
        n_full = prompt.size // ps
        return [prompt[i * ps:(i + 1) * ps].tobytes()
                for i in range(n_full)]

    def match(self, prompt):
        """Walk the prompt's full blocks down the tree. Returns
        (pages, matched_tokens): the shared pages (refcount bumped —
        the caller now owns one reference each) covering the longest
        cached prefix. A digest hit whose stored tokens or parent
        disagree (hash collision) stops the walk — correctness never
        rests on the hash."""
        pages, parent = [], b""
        for block in self._blocks(prompt):
            digest = _block_digest(parent, block)
            node = self.nodes.get(digest)
            if node is None or node.block != block \
                    or node.parent != parent:
                break
            node.last_used = next(self._stamp)
            self.pool.incref(node.page)
            pages.append(node.page)
            parent = digest
        matched = len(pages) * self.pool.page_size
        if pages:
            self.stats["hits"] += 1
            self.stats["matched_tokens"] += matched
        else:
            self.stats["misses"] += 1
        return pages, matched

    def insert(self, prompt, pages):
        """Register a prefilled prompt's FULL blocks: `pages[i]` holds
        block i's K/V. Existing nodes are refreshed (their page stays
        authoritative — concurrent identical prefills do not
        duplicate); new nodes adopt the request's page with one more
        reference. Safe to call again after a requeue replay."""
        parent = b""
        for i, block in enumerate(self._blocks(prompt)):
            if i >= len(pages):
                break
            digest = _block_digest(parent, block)
            node = self.nodes.get(digest)
            if node is not None and (node.block != block
                                     or node.parent != parent):
                break                    # collision: stop registering
            if node is None:
                node = _Node(digest, parent, block, pages[i],
                             next(self._stamp))
                self.nodes[digest] = node
                if parent == b"":
                    self.roots.add(digest)
                else:
                    self.nodes[parent].children.add(digest)
                self.pool.incref(node.page)
                self.stats["inserted_pages"] += 1
            else:
                node.last_used = next(self._stamp)
            parent = digest

    def evict(self, need_free):
        """Drop tree references, LRU leaf first, until the pool has
        `need_free` free pages or no leaf remains. Returns the number of
        nodes evicted (a node whose page is still shared by a running
        request is evicted from the TREE but only returns to the pool
        when that request drains)."""
        evicted = 0
        while self.pool.free_pages() < need_free:
            leaves = [n for n in self.nodes.values() if not n.children]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.last_used)
            self._drop(victim)
            evicted += 1
        return evicted

    def clear(self):
        """Drop every tree reference (server shutdown)."""
        n = len(self.nodes)
        while self.nodes:
            leaves = [d for d, node in self.nodes.items()
                      if not node.children]
            for d in leaves:
                self._drop(self.nodes[d])
        return n

    def _drop(self, node):
        del self.nodes[node.digest]
        if node.parent == b"":
            self.roots.discard(node.digest)
        else:
            p = self.nodes.get(node.parent)
            if p is not None:
                p.children.discard(node.digest)
        self.pool.decref(node.page)
        self.stats["evicted_pages"] += 1
