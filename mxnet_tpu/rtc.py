"""Runtime kernel compilation facade (reference: `src/common/rtc.cc`,
`python/mxnet/rtc.py` — NVRTC compilation of user CUDA source).

TPU-native equivalent: user-supplied **Pallas** kernels compiled at runtime
by Mosaic/XLA. `PallasModule` mirrors `mx.rtc.CudaModule`'s shape —
construct from kernel source or a kernel function, `get_kernel` binds a
signature, `launch` runs on device — but the kernel language is Pallas
(grid + BlockSpecs) instead of CUDA C, because that is what the hardware
JIT-compiles here. Raw CUDA source is rejected with a clear error.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ndarray import NDArray

__all__ = ["PallasModule", "CudaModule", "Kernel"]


class Kernel:
    """A launchable compiled kernel (reference: rtc.CudaModule.Kernel)."""

    def __init__(self, fn, name):
        self._fn = fn
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Run the kernel. grid/block dims are accepted for API parity but
        ignored — Pallas grids are part of the kernel definition, and XLA
        owns scheduling."""
        raw = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
               for a in args]
        out = self._fn(*raw)
        if isinstance(out, tuple):
            return tuple(NDArray(o) for o in out)
        return NDArray(out)

    __call__ = launch


class PallasModule:
    """Compile-and-run container for user Pallas kernels.

    Two construction modes:
      * `PallasModule(source=...)` — a string of Python source defining one
        or more functions that call `pl.pallas_call`; exec'd with
        jax/jnp/pl/pltpu in scope (the NVRTC-analog path).
      * `PallasModule(kernels={'name': fn})` — pre-built callables.
    """

    def __init__(self, source=None, kernels=None, exports=None):
        self._kernels = dict(kernels or {})
        if source is not None:
            if "__global__" in source or "blockIdx" in source:
                raise ValueError(
                    "CUDA source is not supported on TPU; write a Pallas "
                    "kernel (see /opt/skills/guides/pallas_guide.md and "
                    "mxnet_tpu.pallas_ops for examples)")
            from jax.experimental import pallas as pl
            try:
                from jax.experimental.pallas import tpu as pltpu
            except ImportError:  # CPU-only envs
                pltpu = None
            ns = {"jax": jax, "jnp": jnp, "pl": pl, "pltpu": pltpu}
            exec(compile(source, "<rtc>", "exec"), ns)
            for name, obj in ns.items():
                if callable(obj) and not name.startswith("_") and \
                        name not in ("jax", "jnp", "pl", "pltpu"):
                    self._kernels.setdefault(name, obj)
        if exports is not None:
            missing = set(exports) - set(self._kernels)
            if missing:
                raise ValueError(f"exported kernels not found: {sorted(missing)}")

    def get_kernel(self, name, signature=None):
        """Bind a kernel by name (signature accepted for parity; Pallas
        kernels carry their own typing)."""
        if name not in self._kernels:
            raise KeyError(f"kernel {name!r} not in module "
                           f"(have {sorted(self._kernels)})")
        return Kernel(jax.jit(self._kernels[name]), name)


def CudaModule(*args, **kwargs):
    """Reference-named constructor; exists to give reference users a clear
    landing point."""
    raise NotImplementedError(
        "mx.rtc.CudaModule compiles CUDA, which TPU cannot run. Use "
        "mx.rtc.PallasModule with a Pallas kernel instead.")
