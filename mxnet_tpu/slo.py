"""mx.slo — per-request serving observability.

Every observability layer so far (mx.telemetry, mx.trace, mx.scope) is
step- or rank-scoped; this module is REQUEST-scoped: it turns the
serving stack's opaque verdict counters into attributable per-request
latency budgets. Three pieces:

  * **request journal** — while armed, every `serve.Request` carries a
    monotone event timeline (submit, admit/reject/shed, first dispatch,
    per-token generation timestamps → time-between-tokens, stream
    delivery timestamps, degradation/requeue/retry transitions, the
    terminal verdict), recorded at the existing serve.py lifecycle
    points. Timestamps live on the shared monotonic trace epoch
    (`util.perf_to_us`), so journals and mx.trace spans — which carry
    the request id in their args — join on one timeline.
  * **SLO objectives & burn rate** — the `slo_ttft_ms` / `slo_tbt_ms` /
    `slo_availability` knobs classify each terminated request good/bad.
    Classifications feed a multi-window rolling error-budget tracker
    (`BurnTracker`, injectable clock): burn rate = observed bad
    fraction / allowed bad fraction (1 - slo_availability), per window
    (fast 5m + slow 1h by default). Burn above `slo_burn_alert` emits a
    telemetry alert event, a diagnostics flight-ring entry and an alert
    record in the access log — the fast window reacts to a fresh
    overload long before the slow window confirms it is sustained.
  * **tail-sampled exemplars** — full journals persist to
    `slo_dir/<rank>/access.jsonl` only for SLO-violating, degraded or
    slower-than-running-p99 requests, plus a 1-in-`slo_sample_every`
    healthy sample — the hot path stays cheap while every bad request
    is explained. `tools/slo_report.py` renders the per-phase (queue /
    prefill / decode / stream) attribution; mx.scope `/statusz` serves
    the live `slo` section the gang aggregator merges.

Classification semantics: `completed` requests are good unless an
enabled latency objective is violated (TTFT is CLIENT-visible — first
delivered token when a consumer streams, first generated token
otherwise; TBT is the worst gap between consecutive generated tokens).
`rejected` / `shed` / `expired` / `failed` requests violate the
availability objective. `cancelled` requests are the client's own
doing and are excluded from the error budget (still journaled).

Cost model: DISABLED (the default) is the production fast path — every
hook site in serve.py checks one module bool and allocates nothing
(`ci/run.sh sanity` asserts zero calls and `Request._slo_j is None`).
"""
from __future__ import annotations

import collections
import json
import os
import sys
import time

from . import _locklint
from . import config as _config
from . import diagnostics as _diagnostics
from . import telemetry as _telemetry
from . import util as _util

__all__ = [
    "enable", "disable", "enabled", "reset", "snapshot", "BurnTracker",
    "Journal", "access_path", "flush_summary", "objectives",
]

# reentrant: _finalize holds the lock while the burn tracker fires
# _on_alert, which records the first-alert marker and appends the alert
# record under the same lock
_lock = _locklint.make_rlock("slo.module")
_enabled = False            # the fast-path bool; serve hook sites read it
_dir = ""                   # exemplar base dir ("" = classify only)
_rank_override = None
_clock = time.monotonic     # burn-window clock (injectable for tests)
_tracker = None             # BurnTracker while enabled
_sample_every = 10
_objectives = None          # dict while enabled
_seq = 0                    # finalized-request counter (drives sampling)
_meta_paths = set()
_write_warned = False
_first_alert = None         # {"window","burn","ts_s","wall"} of alert #1

# bounded aggregates for snapshot()/bench (client-visible milliseconds)
_MAX_SAMPLES = 4096
_ttfts = collections.deque(maxlen=_MAX_SAMPLES)
_tbts = collections.deque(maxlen=_MAX_SAMPLES)
_counts = collections.Counter()        # terminal outcome -> requests
_violations = collections.Counter()    # objective -> bad classifications
_phase_ms = {"queue": 0.0, "prefill": 0.0, "decode": 0.0, "stream": 0.0}
_phase_n = 0
_exemplars = 0

_M_BURN = _telemetry.gauge(
    "slo_burn_rate", "rolling error-budget burn rate per window (bad "
    "fraction / allowed bad fraction; 1.0 consumes the budget exactly "
    "at the sustainable rate, above slo_burn_alert fires an alert)")
_M_REQS = _telemetry.counter(
    "slo_requests_total", "terminated serving requests classified "
    "against the SLO objectives, by verdict (good / bad; cancelled "
    "requests are excluded from the error budget)")
_M_VIOL = _telemetry.counter(
    "slo_violations_total", "SLO objective violations by objective "
    "(ttft / tbt / availability) — one request may violate several")
_M_ALERTS = _telemetry.counter(
    "slo_alerts_total", "burn-rate alerts fired, by window")
_M_EXEMPLARS = _telemetry.counter(
    "slo_exemplars_total", "request journals persisted to access.jsonl "
    "(tail-sampled: bad / degraded / slow-p99 / 1-in-N)")


def enabled():
    """True while mx.slo is armed (serve's hook sites read the module
    bool `_enabled` directly; this is the public spelling)."""
    return _enabled


def enable(slo_dir=None, rank=None, clock=None, sample_every=None):
    """Arm per-request journaling. Arguments override the `slo_dir` /
    `slo_sample_every` knobs (read once here — the per-token hot path
    never touches the config registry). `clock` injects the burn-window
    clock for deterministic tests."""
    global _enabled, _dir, _rank_override, _clock, _tracker
    global _sample_every, _objectives
    with _lock:
        if slo_dir is not None:
            _dir = str(slo_dir)
        elif not _dir:
            _dir = _config.get("slo_dir")
        if rank is not None:
            _rank_override = int(rank)
        if clock is not None:
            _clock = clock
        _sample_every = int(sample_every if sample_every is not None
                            else _config.get("slo_sample_every"))
        _objectives = {
            "ttft_ms": float(_config.get("slo_ttft_ms")),
            "tbt_ms": float(_config.get("slo_tbt_ms")),
            "availability": float(_config.get("slo_availability")),
        }
        if _tracker is None:
            _tracker = BurnTracker(
                availability=_objectives["availability"],
                windows=(("fast", float(_config.get("slo_window_fast_s"))),
                         ("slow", float(_config.get("slo_window_slow_s")))),
                alert=float(_config.get("slo_burn_alert")),
                clock=_clock, on_alert=_on_alert)
        _enabled = True


def disable():
    """Disarm the hooks; a configured access log gets a final summary
    record so offline reports see the window verdicts."""
    global _enabled
    if _enabled and _dir:
        try:
            flush_summary()
        except OSError:
            pass
    _enabled = False


def reset():
    """Drop recorded state (tests and run boundaries). While disabled
    everything is released, restoring the zero-allocation fast path."""
    global _dir, _rank_override, _clock, _tracker, _sample_every
    global _objectives, _seq, _write_warned, _first_alert, _phase_n
    global _exemplars
    with _lock:
        _ttfts.clear()
        _tbts.clear()
        _counts.clear()
        _violations.clear()
        for k in _phase_ms:
            _phase_ms[k] = 0.0
        _phase_n = 0
        _seq = 0
        _exemplars = 0
        _meta_paths.clear()
        _write_warned = False
        _first_alert = None
        _tracker = None
        if not _enabled:
            _dir = ""
            _rank_override = None
            _clock = time.monotonic
            _objectives = None


def objectives():
    """The armed objective thresholds (None while disabled)."""
    return dict(_objectives) if _objectives else None


def _rank():
    if _rank_override is not None:
        return _rank_override
    for var in ("JAX_PROCESS_ID", "DMLC_WORKER_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def access_path():
    """Where this rank's exemplar journals land (None when slo_dir is
    unset)."""
    if not _dir:
        return None
    return os.path.join(_dir, str(_rank()), "access.jsonl")


# ---------------------------------------------------------------------------
# burn-rate tracker
# ---------------------------------------------------------------------------

class BurnTracker:
    """Multi-window rolling error-budget burn rate (SRE-style).

    Each classification lands in a coarse time bucket; a window's burn
    rate is its bad fraction divided by the allowed bad fraction
    (1 - availability target). 1.0 burns the budget exactly at the
    sustainable rate; `alert`+ fires `on_alert(window, burn)` once per
    excursion (re-arming only after the window cools below the
    threshold). The FAST window spikes on a fresh overload while the
    SLOW window is still diluted by history — and conversely stays hot
    after a long burn the fast window has already forgotten: alert on
    fast to react, on slow to confirm. The clock is injectable so the
    window math is deterministically testable."""

    def __init__(self, availability=0.999, windows=(("fast", 300.0),
                                                    ("slow", 3600.0)),
                 alert=2.0, clock=time.monotonic, on_alert=None):
        self.budget = max(1e-9, 1.0 - float(availability))
        self.windows = [(str(n), float(s)) for n, s in windows]
        self.alert = float(alert)
        self._clock = clock
        self._on_alert = on_alert
        self._span = max(s for _, s in self.windows)
        # bucket granularity: 1/60th of the fastest window (5 s for 5 m)
        self._bucket_s = max(0.001, min(s for _, s in self.windows) / 60.0)
        self._buckets = collections.deque()   # [start_s, good, bad]
        self._alerting = {n: False for n, _ in self.windows}
        self.alerts = collections.Counter()   # window -> alerts fired

    def record(self, good, now=None):
        """Classify one terminated request into the current bucket and
        re-evaluate every window's burn rate (firing alerts)."""
        now = self._clock() if now is None else now
        start = now - (now % self._bucket_s)
        if self._buckets and self._buckets[-1][0] == start:
            b = self._buckets[-1]
        else:
            b = [start, 0, 0]
            self._buckets.append(b)
        b[1 if good else 2] += 1
        self._prune(now)
        rates = self.burn_rates(now)
        for name, _span in self.windows:
            rate = rates.get(name)
            if rate is None:
                continue
            if rate >= self.alert:
                if not self._alerting[name]:
                    self._alerting[name] = True
                    self.alerts[name] += 1
                    if self._on_alert is not None:
                        self._on_alert(name, rate)
            else:
                self._alerting[name] = False
        return rates

    def _prune(self, now):
        horizon = now - self._span - self._bucket_s
        while self._buckets and self._buckets[0][0] < horizon:
            self._buckets.popleft()

    def burn_rates(self, now=None):
        """{window_name: burn rate} — None for a window that saw no
        classified traffic (no data is not 'no burn')."""
        now = self._clock() if now is None else now
        out = {}
        for name, span in self.windows:
            good = bad = 0
            for start, g, b in self._buckets:
                if start > now - span:
                    good += g
                    bad += b
            total = good + bad
            out[name] = None if total == 0 \
                else (bad / total) / self.budget
        return out


def _on_alert(window, burn):
    global _first_alert
    rec = {"window": window, "burn": round(burn, 3),
           "ts_s": round(_clock(), 3), "wall": time.time()}
    with _lock:
        if _first_alert is None:
            _first_alert = dict(rec)
    print(f"mx.slo: error budget burning hot: window={window} "
          f"burn_rate={burn:.2f} (alert threshold "
          f"{_tracker.alert if _tracker else '?'})", file=sys.stderr)
    if _telemetry._enabled:
        _M_ALERTS.labels(window=window).inc()
        _telemetry.event("slo_alert", **rec)
    if _diagnostics._enabled:
        _diagnostics.record_event("slo", action="burn_alert", **rec)
    _append_record({"kind": "alert", **rec})


# ---------------------------------------------------------------------------
# request journal
# ---------------------------------------------------------------------------

class Journal:
    """The per-request event timeline. All `*_pc` fields are raw
    time.perf_counter() readings (seconds) on the shared trace epoch;
    `events` holds (pc, kind, extra-dict-or-None) transitions beyond
    the dedicated fields."""

    __slots__ = ("req_id", "submit_pc", "admit_pc", "dispatch_pc",
                 "token_pcs", "deliver_first_pc", "deliver_last_pc",
                 "delivered", "stream_open", "events", "retries",
                 "outcome", "verdict", "finish_pc", "finalized",
                 "bucket")

    def __init__(self, req_id, submit_pc):
        self.req_id = req_id
        self.submit_pc = submit_pc
        self.admit_pc = None
        self.dispatch_pc = None          # first decode dispatch
        self.token_pcs = []              # generation time per NEW token
        self.deliver_first_pc = None     # stream-side (client-visible)
        self.deliver_last_pc = None
        self.delivered = 0
        self.stream_open = False
        self.events = []
        self.retries = 0
        self.outcome = None
        self.verdict = None
        self.finish_pc = None
        self.finalized = False
        self.bucket = None

    # -- derived timings (milliseconds; None when the phase never ran) --
    def queue_ms(self):
        if self.admit_pc is None:
            return None
        return (self.admit_pc - self.submit_pc) * 1e3

    def prefill_ms(self):
        """Admission to the first generated token: the prompt replay
        through the decode executable (prefill IS decode here)."""
        if self.admit_pc is None or not self.token_pcs:
            return None
        return (self.token_pcs[0] - self.admit_pc) * 1e3

    def decode_ms(self):
        if len(self.token_pcs) < 2:
            return None
        return (self.token_pcs[-1] - self.token_pcs[0]) * 1e3

    def stream_ms(self):
        """First-token delivery lag: generation to the client actually
        receiving it (None when nobody streamed)."""
        if self.deliver_first_pc is None or not self.token_pcs:
            return None
        return max(0.0, (self.deliver_first_pc - self.token_pcs[0]) * 1e3)

    def ttft_ms(self):
        """CLIENT-visible time to first token: submit to first delivery
        when a consumer streamed, submit to first generation otherwise."""
        if self.deliver_first_pc is not None:
            return (self.deliver_first_pc - self.submit_pc) * 1e3
        if self.token_pcs:
            return (self.token_pcs[0] - self.submit_pc) * 1e3
        return None

    def tbt_ms(self):
        """Gaps between consecutive generated tokens, in ms (includes a
        requeue's replay pause — the client really waited that long)."""
        pcs = self.token_pcs
        return [(b - a) * 1e3 for a, b in zip(pcs, pcs[1:])]

    def timeline(self):
        """The monotone event timeline, ms relative to submit."""
        rel = lambda pc: round((pc - self.submit_pc) * 1e3, 3)  # noqa: E731
        out = [{"t_ms": 0.0, "event": "submit"}]
        if self.admit_pc is not None:
            ev = {"t_ms": rel(self.admit_pc), "event": "admit"}
            if self.bucket is not None:
                ev["bucket"] = self.bucket
            out.append(ev)
        if self.dispatch_pc is not None:
            out.append({"t_ms": rel(self.dispatch_pc),
                        "event": "first_dispatch"})
        if self.token_pcs:
            out.append({"t_ms": rel(self.token_pcs[0]),
                        "event": "first_token"})
        for pc, kind, extra in self.events:
            ev = {"t_ms": rel(pc), "event": kind}
            if extra:
                ev.update(extra)
            out.append(ev)
        if self.deliver_first_pc is not None:
            out.append({"t_ms": rel(self.deliver_first_pc),
                        "event": "first_delivery"})
        if self.finish_pc is not None:
            ev = {"t_ms": rel(self.finish_pc), "event": "finish"}
            if self.outcome:
                ev["outcome"] = self.outcome
            if self.verdict:
                ev["verdict"] = self.verdict
            out.append(ev)
        out.sort(key=lambda e: e["t_ms"])
        return out


# -- serve.py hook sites (callers gate on the module bool: none of these
#    is ever reached while disabled; ci sanity counts the calls) --------

def note_submit(req):
    """Attach a journal at submit time — before any admission verdict,
    so rejected/shed requests are journaled too."""
    req._slo_j = Journal(req.id, req._submit_perf)


def note_admit(req, bucket):
    j = req._slo_j
    j.admit_pc = req._admit_perf
    j.bucket = int(bucket)


def note_first_dispatch(req):
    j = req._slo_j
    if j.dispatch_pc is None:
        j.dispatch_pc = time.perf_counter()


def note_token(req):
    """Generation timestamp for one NEW token (serve._emit's replay
    high-water mark keeps requeue replays from double-stamping)."""
    req._slo_j.token_pcs.append(time.perf_counter())


def note_event(req, kind, **extra):
    """Degradation / requeue / retry transition on the timeline."""
    j = req._slo_j
    if kind == "retry":
        j.retries += 1
    j.events.append((time.perf_counter(), str(kind), extra or None))


def note_stream_start(req):
    j = req._slo_j
    if not j.finalized:
        j.stream_open = True


def note_delivered(req):
    """Client-side delivery stamp (after any slow_client stall) — the
    half of TTFT the scheduler cannot see."""
    j = req._slo_j
    pc = time.perf_counter()
    if j.deliver_first_pc is None:
        j.deliver_first_pc = pc
    j.deliver_last_pc = pc
    j.delivered += 1


def note_stream_end(req):
    """The consumer finished (sentinel, break, or GC'd generator):
    delivery timestamps are complete — finalize if the request already
    terminated."""
    j = req._slo_j
    j.stream_open = False
    if j.outcome is not None:
        _finalize(req, j)


def note_finish(req, outcome, verdict):
    """Terminal transition. Finalizes (classify + maybe persist) now
    unless a live stream consumer is still draining delivery stamps —
    then note_stream_end finalizes with the client-visible timings."""
    j = req._slo_j
    j.outcome = str(outcome)
    j.verdict = verdict
    j.finish_pc = req._finish_perf or time.perf_counter()
    if not j.stream_open:
        _finalize(req, j)


# ---------------------------------------------------------------------------
# classification, aggregation, exemplar persistence
# ---------------------------------------------------------------------------

def _percentile(vals, q):
    if not vals:
        return None
    s = sorted(vals)
    idx = min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))
    return s[idx]


def _classify(j):
    """The SLO verdict for one terminated request: (good, [objective
    violations]). Cancelled requests return (None, []) — excluded."""
    if j.outcome == "cancelled":
        return None, []
    bad = []
    if j.outcome != "completed":
        bad.append("availability")
    obj = _objectives or {}
    ttft = j.ttft_ms()
    limit = obj.get("ttft_ms") or 0.0
    if limit > 0 and ttft is not None and ttft > limit:
        bad.append("ttft")
    limit = obj.get("tbt_ms") or 0.0
    if limit > 0:
        gaps = j.tbt_ms()
        if gaps and max(gaps) > limit:
            bad.append("tbt")
    return not bad, bad


def _finalize(req, j):
    """Classify against the objectives, feed the burn windows and
    aggregates, and tail-sample the full journal into access.jsonl."""
    global _seq, _phase_n, _exemplars
    with _lock:
        if j.finalized:
            return
        j.finalized = True
        _seq += 1
        seq = _seq
        good, violated = _classify(j)
        _counts[j.outcome] += 1
        for obj in violated:
            _violations[obj] += 1
        ttft = j.ttft_ms()
        slow_p99 = False
        if ttft is not None:
            if len(_ttfts) >= 20:
                p99 = _percentile(_ttfts, 99)
                slow_p99 = p99 is not None and ttft >= p99
            _ttfts.append(ttft)
        for gap in j.tbt_ms():
            _tbts.append(gap)
        phases = {"queue": j.queue_ms(), "prefill": j.prefill_ms(),
                  "decode": j.decode_ms(), "stream": j.stream_ms()}
        if any(v is not None for v in phases.values()):
            _phase_n += 1
            for k, v in phases.items():
                if v is not None:
                    _phase_ms[k] += v
        rates = _tracker.record(good) if _tracker is not None \
            and good is not None else {}
    if _telemetry._enabled:
        if good is not None:
            _M_REQS.labels(verdict="good" if good else "bad").inc()
        for obj in violated:
            _M_VIOL.labels(objective=obj).inc()
        for w, r in rates.items():
            if r is not None:
                _M_BURN.labels(window=w).set(round(r, 4))
    why = []
    if violated:
        why.append("slo:" + ",".join(violated))
    if req.degraded or req.requeues:
        why.append("degraded")
    if slow_p99:
        why.append("slow-p99")
    if _sample_every > 0 and seq % _sample_every == 0:
        why.append("sampled")
    if why and _dir:
        if _append_record(_access_record(req, j, good, violated, why,
                                         phases)):
            with _lock:
                _exemplars += 1
            if _telemetry._enabled:
                _M_EXEMPLARS.inc()


def _access_record(req, j, good, violated, why, phases):
    gaps = j.tbt_ms()
    rec = {
        "kind": "access", "schema": 1, "rank": _rank(), "req": j.req_id,
        "outcome": j.outcome, "verdict": j.verdict,
        "good": good, "violations": violated, "why": why,
        "prompt_len": int(req.prompt.size),
        "requested_new": req.requested_new_tokens,
        "new_tokens": len(req.tokens),
        "delivered": j.delivered,
        "requeues": req.requeues, "degraded": req.degraded,
        "retries": j.retries,
        "queue_ms": _r3(phases["queue"]),
        "prefill_ms": _r3(phases["prefill"]),
        "decode_ms": _r3(phases["decode"]),
        "stream_ms": _r3(phases["stream"]),
        "ttft_ms": _r3(j.ttft_ms()),
        "tbt_max_ms": _r3(max(gaps)) if gaps else None,
        "tbt_p99_ms": _r3(_percentile(gaps, 99)) if gaps else None,
        "submit_us": round(_util.perf_to_us(j.submit_pc), 1),
        "timeline": j.timeline(),
    }
    return rec


def _r3(v):
    return None if v is None else round(v, 3)


def _meta_record():
    return {"kind": "meta", "schema": 1, "rank": _rank(),
            "pid": os.getpid(), "ts": time.time(),
            "epoch_unix_ns": _util.epoch_unix_ns(),
            "objectives": dict(_objectives or {}),
            "sample_every": _sample_every}


def _append_record(rec):
    """Append one record to this rank's access.jsonl (meta line first,
    once per path). Exemplars are tail-sampled — rare by design — so a
    plain line-buffered append is the right tool. An unwritable dir
    warns once and drops records (journaling must not take the serving
    path down with it)."""
    global _write_warned
    path = access_path()
    if path is None:
        return False
    with _lock:
        need_meta = path not in _meta_paths
        _meta_paths.add(path)
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", buffering=1) as f:
            if need_meta:
                f.write(json.dumps(_meta_record()) + "\n")
            f.write(json.dumps(rec) + "\n")
        return True
    except OSError as e:
        with _lock:
            if need_meta:
                _meta_paths.discard(path)
        if not _write_warned:
            _write_warned = True
            import warnings
            warnings.warn(f"mx.slo: access log write to {path!r} failed: "
                          f"{e}; exemplars are dropped (warning once)")
        return False


def flush_summary():
    """Append a summary record (window burn rates, counts, percentiles)
    to access.jsonl — the offline half of the SLO verdict. Called by
    disable(); safe to call repeatedly (each call appends a fresher
    summary; slo_report keeps the last per rank)."""
    snap = snapshot()
    snap["kind"] = "summary"
    snap["schema"] = 1
    snap["rank"] = _rank()
    snap["ts"] = time.time()
    if _append_record(snap):
        return access_path()
    return None


def snapshot():
    """The live `slo` section mx.scope /statusz serves (plain dict,
    merged across ranks by the gang aggregator): per-outcome counts,
    TTFT/TBT percentiles, phase shares, burn rates, violations."""
    with _lock:
        ttfts = list(_ttfts)
        tbts = list(_tbts)
        counts = dict(_counts)
        viol = dict(_violations)
        phase = dict(_phase_ms)
        n = _phase_n
        tracker = _tracker
        first_alert = dict(_first_alert) if _first_alert else None
        exemplars = _exemplars
    total_phase = sum(phase.values())
    out = {
        "enabled": _enabled,
        "objectives": dict(_objectives or {}),
        "counts": counts,
        "classified": sum(counts.values()),
        "ttft_p50_ms": _r3(_percentile(ttfts, 50)),
        "ttft_p99_ms": _r3(_percentile(ttfts, 99)),
        "tbt_p50_ms": _r3(_percentile(tbts, 50)),
        "tbt_p99_ms": _r3(_percentile(tbts, 99)),
        "violations": viol,
        "phase_share": {k: round(v / total_phase, 4) if total_phase else
                        None for k, v in phase.items()},
        "phase_ms_mean": {k: _r3(v / n) if n else None
                          for k, v in phase.items()},
        "burn_rate": {w: (None if r is None else round(r, 4))
                      for w, r in (tracker.burn_rates().items()
                                   if tracker else ())},
        "alerts": dict(tracker.alerts) if tracker else {},
        "first_alert": first_alert,
        "exemplars_written": exemplars,
        "access_path": access_path(),
    }
    return out


if _config.get("slo") == "on":
    enable()
